"""Reader decorators + DataLoader prefetch (reference: reader decorators,
PyReader tests)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import reader as rd


def test_decorators():
    def r():
        yield from range(10)

    b = rd.batch(r, 3)
    batches = list(b())
    assert batches[0] == [0, 1, 2] and len(batches) == 4
    b2 = rd.batch(r, 3, drop_last=True)
    assert len(list(b2())) == 3
    s = rd.shuffle(r, 5)
    assert sorted(list(s())) == list(range(10))
    f = rd.firstn(r, 4)
    assert list(f()) == [0, 1, 2, 3]
    m = rd.map_readers(lambda a, b: a + b, r, r)
    assert list(m())[:3] == [0, 2, 4]
    x = rd.xmap_readers(lambda v: v * 2, r, 2, 4)
    assert sorted(list(x())) == [v * 2 for v in range(10)]


def test_dataloader_trains():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(x, 1), y)
        )
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)

    def gen():
        for _ in range(12):
            xv = rng.rand(8, 4).astype("f4")
            yield {"x": xv, "y": xv.sum(1, keepdims=True)}

    loader = fluid.DataLoader.from_generator([x, y], capacity=3).set_batch_generator(gen)
    losses = [float(exe.run(main, feed=f, fetch_list=[loss], scope=scope)[0][0]) for f in loader]
    assert len(losses) == 12
    assert losses[-1] < losses[0]


def test_shuffle_deterministic_under_seed():
    def r():
        yield from range(20)

    a = list(rd.shuffle(r, 8, seed=123)())
    b = list(rd.shuffle(r, 8, seed=123)())
    c = list(rd.shuffle(r, 8, seed=7)())
    assert a == b, "same seed must give the same order"
    assert sorted(a) == list(range(20))
    assert a != c, "different seeds should permute differently"
    # program-level random_seed is the default seed source
    fluid.default_main_program().random_seed = 5
    d1 = list(rd.shuffle(r, 8)())
    d2 = list(rd.shuffle(r, 8)())
    assert d1 == d2


def test_dataloader_per_name_sharding_dict():
    """Regression: `sharding` documented as an optional dict name->Sharding
    was passed WHOLE to jax.device_put; it must be looked up per feed name
    (missing names fall back to `device`)."""
    import jax

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
    devs = jax.local_devices()
    dev_x, dev_fallback = devs[0], devs[1 % len(devs)]

    def gen():
        yield {"x": np.zeros((2, 4), "f4"), "y": np.zeros((2, 1), "f4")}

    loader = fluid.DataLoader.from_generator(
        [x, y], capacity=2, device=dev_fallback,
        sharding={"x": jax.sharding.SingleDeviceSharding(dev_x)},
    ).set_batch_generator(gen)
    (batch,) = list(loader)
    assert list(batch["x"].devices()) == [dev_x]
    assert list(batch["y"].devices()) == [dev_fallback]


def test_dataloader_propagates_producer_exception():
    """A user data bug must surface as the original exception (with the
    generator's traceback), not a bare RuntimeError from the loader."""
    import traceback

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")

    def bad_gen():
        yield {"x": np.zeros((2, 4), "f4")}
        raise ValueError("user data bug at sample 1")

    loader = fluid.DataLoader.from_generator([x], capacity=2).set_batch_generator(bad_gen)
    it = iter(loader)
    next(it)
    try:
        next(it)
    except ValueError as e:
        assert "user data bug at sample 1" in str(e)
        tb = "".join(traceback.format_exception(type(e), e, e.__traceback__))
        assert "bad_gen" in tb, f"original generator frame lost:\n{tb}"
    else:
        raise AssertionError("producer exception was swallowed")


def test_datafeeder_shapes():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [3, 8, 8], dtype="float32")
        label = fluid.layers.data("label", [1], dtype="int64")
    feeder = fluid.DataFeeder([img, label])
    samples = [(np.zeros((3, 8, 8)), 7), (np.ones((3, 8, 8)), 2)]
    feed = feeder.feed(samples)
    assert feed["img"].shape == (2, 3, 8, 8) and feed["img"].dtype == np.float32
    assert feed["label"].shape == (2, 1) and feed["label"].dtype == np.int64


def test_xmap_mapper_exception_reraised_not_hung():
    """A mapper exception used to kill the worker thread without posting
    END, leaving the consumer blocked on out_q.get() forever; it must be
    re-raised in the consumer instead (ISSUE 3 satellite)."""
    import pytest

    def r():
        yield from range(8)

    def bad_mapper(v):
        if v == 3:
            raise ValueError(f"cannot map sample {v}")
        return v * 2

    for order in (False, True):
        x = rd.xmap_readers(bad_mapper, r, 2, 4, order=order)
        with pytest.raises(ValueError, match="cannot map sample 3"):
            list(x())

    # the breadcrumb routes it through the taxonomy as a data failure
    from paddle_tpu.errors import DataError, classify

    x = rd.xmap_readers(bad_mapper, r, 2, 4)
    try:
        list(x())
    except ValueError as e:
        ce = classify(e)
        assert isinstance(ce, DataError) and ce.batch_index == 3


def test_feedspec_shape_mismatch_raises_dataerror_before_lowering():
    """ISSUE 5 acceptance: a shape-mismatched feed dies AT THE FEED
    BOUNDARY, as a DataError naming the slot — no executor, no lowering,
    no opaque XLA error."""
    import pytest

    from paddle_tpu.errors import DataError, classify

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")

    def gen():
        yield {"x": np.zeros((8, 3), "f4")}  # slot expects (-1, 4)

    loader = fluid.DataLoader.from_generator([x], capacity=2).set_batch_generator(gen)
    with pytest.raises(DataError, match="'x'.*shape") as ei:
        list(loader)
    assert ei.value.phase == "feed"
    assert isinstance(classify(ei.value), DataError)


def test_feedspec_dtype_kind_mismatch():
    """int->float widening stays silent (the loader always cast); float
    data into an int slot — a real bug — raises, naming the slot."""
    import pytest

    from paddle_tpu.errors import DataError

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        lbl = fluid.layers.data("label", [1], dtype="int64")
        xf = fluid.layers.data("xf", [2], dtype="float32")

    def bad_gen():
        yield {"label": np.zeros((4, 1), "f4"),  # float into int slot
               "xf": np.zeros((4, 2), "f4")}

    loader = fluid.DataLoader.from_generator([lbl, xf], capacity=2) \
        .set_batch_generator(bad_gen)
    with pytest.raises(DataError, match="'label'.*dtype"):
        list(loader)

    def ok_gen():
        yield {"label": np.zeros((4, 1), "i8"),
               "xf": np.zeros((4, 2), "i4")}  # int->float: fine

    loader = fluid.DataLoader.from_generator([lbl, xf], capacity=2) \
        .set_batch_generator(ok_gen)
    (b,) = list(loader)
    assert b["xf"].dtype == np.float32

    feeder = fluid.DataFeeder([lbl])
    with pytest.raises(DataError, match="'label'"):
        feeder.feed([(np.float32(1.5),), (np.float32(2.5),)])


def test_feedspec_finiteness_under_full_mode():
    import pytest

    from paddle_tpu.errors import DataError

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [2], dtype="float32")

    def nan_gen():
        a = np.zeros((4, 2), "f4")
        a[1, 0] = np.nan
        yield {"x": a}

    loader = fluid.DataLoader.from_generator([x], capacity=2) \
        .set_batch_generator(nan_gen)
    fluid.set_flags({"FLAGS_feed_validation": "full"})
    try:
        with pytest.raises(DataError, match="'x'.*non-finite"):
            list(loader)
    finally:
        fluid.set_flags({"FLAGS_feed_validation": "shape"})
    # default mode: finiteness not scanned (the injector relies on NaNs
    # flowing through to the resolution-time guard)
    loader = fluid.DataLoader.from_generator([x], capacity=2) \
        .set_batch_generator(nan_gen)
    assert len(list(loader)) == 1
    # off: even shape mismatches pass through (caller's problem)
    fluid.set_flags({"FLAGS_feed_validation": "off"})
    try:
        def bad(): yield {"x": np.zeros((4, 7), "f4")}
        loader = fluid.DataLoader.from_generator([x], capacity=2) \
            .set_batch_generator(bad)
        assert len(list(loader)) == 1
    finally:
        fluid.set_flags({"FLAGS_feed_validation": "shape"})


def test_xmap_source_reader_exception_reraised():
    """The feeder thread dying (source reader bug) must surface too."""
    import pytest

    def bad_reader():
        yield 1
        raise OSError("source went away")

    x = rd.xmap_readers(lambda v: v, bad_reader, 2, 4)
    with pytest.raises(OSError, match="source went away"):
        list(x())
