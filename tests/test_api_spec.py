"""API-freeze gate (reference: tools/diff_api.py over API.spec — a public
signature change must come with an explicit API.spec update)."""
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def test_public_api_matches_spec():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import print_signatures

    live = print_signatures.collect()
    spec_path = os.path.join(REPO, "API.spec")
    assert os.path.exists(spec_path), "API.spec missing; run tools/print_signatures.py --update"
    recorded = open(spec_path).read().splitlines()
    live_set, rec_set = set(live), set(recorded)
    added = sorted(live_set - rec_set)
    removed = sorted(rec_set - live_set)
    assert not added and not removed, (
        "public API drifted from API.spec.\n"
        f"added ({len(added)}): {added[:10]}\n"
        f"removed ({len(removed)}): {removed[:10]}\n"
        "If intentional: python tools/print_signatures.py --update"
    )
