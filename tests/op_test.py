"""OpTest harness (reference: python/paddle/fluid/tests/unittests/op_test.py:134).

Same contract as the reference: a test declares `op_type`, numpy inputs,
attrs, and numpy-computed expected outputs; `check_output()` builds a one-op
program and compares; `check_grad()` compares the autodiff gradient (here:
jax.vjp over the lowering) against numeric finite differences.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.program import Program, program_guard
from paddle_tpu.core.scope import Scope


class OpTest:
    """Subclass, implement setUp() setting self.op_type/self.inputs/
    self.outputs/self.attrs, then call check_output()/check_grad()."""

    op_type: str = ""
    inputs: dict = {}
    outputs: dict = {}
    attrs: dict = {}

    # -- helpers ----------------------------------------------------------
    def _build_program(self):
        prog = Program()
        startup = Program()
        feed = {}
        with program_guard(prog, startup):
            block = prog.global_block()
            in_io = {}
            for slot, val in self.inputs.items():
                if isinstance(val, list):  # multi-var slot: [(name, array), ...]
                    names = []
                    for name, arr in val:
                        arr = np.asarray(arr)
                        block.create_var(name, shape=arr.shape, dtype=str(arr.dtype), is_data=True)
                        feed[name] = arr
                        names.append(name)
                    in_io[slot] = names
                else:
                    arr = np.asarray(val)
                    name = f"in_{slot}"
                    block.create_var(name, shape=arr.shape, dtype=_canon(arr.dtype), is_data=True)
                    feed[name] = arr
                    in_io[slot] = [name]
            out_io = {}
            fetch = []
            for slot, val in self.outputs.items():
                if isinstance(val, list):
                    names = []
                    for name, arr in val:
                        block.create_var(name, dtype=_canon(np.asarray(arr).dtype))
                        names.append(name)
                        fetch.append((slot, name, np.asarray(arr)))
                    out_io[slot] = names
                else:
                    name = f"out_{slot}"
                    block.create_var(name, dtype=_canon(np.asarray(val).dtype))
                    out_io[slot] = [name]
                    fetch.append((slot, name, np.asarray(val)))
            block.append_op(self.op_type, inputs=in_io, outputs=out_io, attrs=dict(self.attrs))
        return prog, feed, fetch

    def check_output(self, atol=1e-5, rtol=1e-5, no_check_set=None):
        self.setUp()
        no_check = set(no_check_set or ())
        prog, feed, fetch = self._build_program()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        names = [name for _, name, _ in fetch]
        outs = exe.run(prog, feed=feed, fetch_list=names, scope=scope)
        for (slot, name, expected), got in zip(fetch, outs):
            if slot in no_check:
                continue
            np.testing.assert_allclose(
                got.astype(np.float64) if got.dtype != np.bool_ else got,
                expected.astype(np.float64) if expected.dtype != np.bool_ else expected,
                atol=atol,
                rtol=rtol,
                err_msg=f"op {self.op_type} output {slot}/{name} mismatch",
            )

    def check_grad(self, inputs_to_check, output_name, max_relative_error=0.005,
                   numeric_grad_delta=1e-3, atol=1e-4):
        """Compare vjp-gradients against central finite differences
        (reference: gradient_checker.py)."""
        self.setUp()
        import jax
        import jax.numpy as jnp

        from paddle_tpu.core.lowering import LoweringContext, lower_one
        from paddle_tpu.core.program import Operator

        prog, feed, fetch = self._build_program()
        op = prog.global_block().ops[-1]
        out_slot = next(slot for slot, name, _ in fetch if name == f"out_{output_name}" or slot == output_name)

        feed64 = {k: np.asarray(v) for k, v in feed.items()}

        def run_fn(varying):
            env = {k: jnp.asarray(v) for k, v in feed64.items()}
            env.update({k: v for k, v in varying.items()})
            ctx = LoweringContext(jax.random.PRNGKey(0))
            lower_one(ctx, op, env)
            outs = []
            for slot, name, _ in fetch:
                if slot == out_slot:
                    outs.append(env[name])
            return sum(jnp.sum(o) for o in outs)

        check_names = [f"in_{s}" for s in inputs_to_check]
        varying0 = {n: jnp.asarray(feed64[n]) for n in check_names}
        analytic = jax.grad(run_fn)(varying0)

        for n in check_names:
            base = feed64[n].astype(np.float64)
            num_grad = np.zeros_like(base)
            flat = base.reshape(-1)
            ng_flat = num_grad.reshape(-1)
            for i in range(flat.size):
                orig = flat[i]
                flat[i] = orig + numeric_grad_delta
                plus = float(run_fn({**varying0, n: jnp.asarray(base.reshape(feed64[n].shape).astype(feed64[n].dtype))}))
                flat[i] = orig - numeric_grad_delta
                minus = float(run_fn({**varying0, n: jnp.asarray(base.reshape(feed64[n].shape).astype(feed64[n].dtype))}))
                flat[i] = orig
                ng_flat[i] = (plus - minus) / (2 * numeric_grad_delta)
            a = np.asarray(analytic[n], dtype=np.float64)
            denom = np.maximum(np.abs(num_grad), np.maximum(np.abs(a), 1e-3))
            rel = np.abs(a - num_grad) / denom
            assert rel.max() <= max_relative_error or np.allclose(a, num_grad, atol=atol), (
                f"grad mismatch for {n}: max rel err {rel.max()}"
            )


def _canon(dt):
    from paddle_tpu.core.dtypes import canonical_dtype

    return canonical_dtype(dt)
