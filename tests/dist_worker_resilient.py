"""Gang worker for the distributed chaos suite (ISSUE 4).

Trains RUN_STEPS sync-SGD steps under the FULL distributed-resilience
stack: `fleet.init()` (heartbeat + collective watchdog + bounded
coordination bootstrap), coordinated checkpoints every SAVE_EVERY steps
(rank-0 COMMITTED marker), deterministic fault injection from
FLAGS_fault_spec (kill_worker / stall_worker fire by rank), and
classified exits (43 = peer failure, 44 = collective timeout) so the
gang launcher can tell a resilience death from a crash.

Restart contract: `paddle_tpu.launch run_gang` re-execs this script with
PADDLE_RESTART_NUM > 0; the worker then restores the newest COMMITTED
checkpoint and continues from its step with GLOBAL step numbering.  The
batch for step S is derived from a per-step seeded RNG, so any
restore-and-replay consumes exactly the batches an uninterrupted run
would — the bit-parity property tests/test_dist_chaos.py pins via the
end-state params digest printed on the RESULT line.
"""
import json
import os
import sys

# must be set before jax import
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=1").strip()

import hashlib  # noqa: E402

import numpy as np  # noqa: E402


def build_model():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 90
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [16], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="int64")
        h = fluid.layers.fc(x, 32, act="relu")
        logits = fluid.layers.fc(h, 10)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    return main, startup, loss


def step_batch(step: int, batch: int = 32):
    """The global batch of train step `step`, derived from the step index
    alone — identical whether the step runs in the first incarnation or a
    post-restart replay (the restart-parity property)."""
    rng = np.random.RandomState(1234 + step)
    xg = rng.rand(batch, 16).astype("f4")
    yg = rng.randint(0, 10, size=(batch, 1)).astype("int64")
    return xg, yg


def params_digest(scope) -> str:
    h = hashlib.sha256()
    for name in sorted(scope.local_var_names()):
        try:
            a = np.asarray(scope.find_var(name))
        except Exception:
            continue
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def main():
    import paddle_tpu as fluid
    from paddle_tpu import dist_resilience as dres
    from paddle_tpu import monitor
    from paddle_tpu.errors import DistributedError
    from paddle_tpu.faults import FaultInjector
    from paddle_tpu.fleet import fleet
    from paddle_tpu.monitor import MonitorLogger

    run_steps = int(os.environ.get("RUN_STEPS", "8"))
    save_every = int(os.environ.get("SAVE_EVERY", "2"))
    ckpt_root = os.environ.get("PADDLE_CHECKPOINT_ROOT")
    restart_num = int(os.environ.get("PADDLE_RESTART_NUM", "0"))
    metrics = os.environ.get("PADDLE_METRICS_PATH")

    logger = None
    if metrics:
        monitor.enable()
        # one file per rank (concurrent appenders into one JSONL tear
        # lines) AND per incarnation (counters reset with the process, so
        # mixing incarnations would fake recompile churn into the gates)
        rank_hint = os.environ.get("PADDLE_TRAINER_ID", "0")
        logger = monitor.get_monitor().attach_logger(
            MonitorLogger(f"{metrics}.r{rank_hint}.i{restart_num}"))

    losses = []
    try:
        # the bootstrap is INSIDE the classified handler: a peer that never
        # dials in surfaces as CollectiveTimeoutError from fleet.init's
        # watchdog-bounded barrier, and must exit 44 with a tombstone just
        # like a mid-training failure — not as a raw traceback
        fleet.init()  # heartbeat + watchdog + bounded bootstrap
        rank, world = fleet.worker_index(), fleet.worker_num()
        injector = FaultInjector.from_flags()

        main_p, startup, loss = build_model()
        compiled = fleet.main_program(main_p) if world > 1 else main_p
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())

        cm = None
        if ckpt_root:
            cm = fluid.CheckpointManager(
                ckpt_root, program=main_p, scope=scope, rank=rank,
                world_size=world, mesh=fleet.mesh if world > 1 else None,
                commit_timeout_s=30,
                retry_policy=fluid.RetryPolicy(backoff_base_s=0.01))
        if injector is not None:
            # storage faults (enospc@S:RANK etc.) fire inside the io.py
            # choke point the coordinated saves write through
            injector.arm_io()

        start = 0
        restored = None
        if cm is not None and restart_num > 0:
            restored = cm.restore(scope=scope)
        if restored is None:
            exe.run(startup, scope=scope)
        else:
            start = restored

        per = 32 // world
        for step in range(start, run_steps):
            xg, yg = step_batch(step)
            if injector is not None:
                injector.on_dispatch(step)  # kill_worker/stall_worker point
            (lv,) = exe.run(compiled,
                            feed={"x": xg[rank * per:(rank + 1) * per],
                                  "y": yg[rank * per:(rank + 1) * per]},
                            fetch_list=[loss], scope=scope)
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
            done = step + 1
            if (cm is not None and save_every and done % save_every == 0
                    and done < run_steps):
                cm.save(step=done)
    except DistributedError as e:
        # classified gang failure: tombstone so live peers learn NOW, then
        # exit with the code the launcher keys restart decisions on.
        # os._exit, not sys.exit — a thread abandoned inside a wedged gloo
        # collective must not keep this corpse half-alive.
        print(f"DIST_FAILURE {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)
        if logger is not None:
            logger.write_snapshot()
        dres.shutdown_health(mark_down=True)
        os._exit(dres.exit_code_for(e))

    print("RESULT " + json.dumps({
        "rank": rank, "world": world, "restart_num": restart_num,
        "start_step": start, "steps_run": len(losses), "losses": losses,
        "ckpt_rounds_skipped": cm.storage_rounds_skipped if cm else 0,
        "ckpt_recoveries": cm.storage_recoveries if cm else 0,
        "ckpt_degraded": bool(cm.degraded) if cm else False,
        "params_sha": params_digest(scope)}), flush=True)
    if logger is not None:
        logger.write_snapshot()
    dres.shutdown_health()


if __name__ == "__main__":
    main()
