"""Data-parallel / mesh tests on the 8-device virtual CPU mesh
(reference test pattern: test_dist_base.py check_with_place — distributed
losses must match single-process losses)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def _build(seed=5):
    main = fluid.Program()
    startup = fluid.Program()
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [16], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="int64")
        h = fluid.layers.fc(x, size=32, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _train(program, startup, loss, scope, steps=8):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(steps):
        xv = rng.rand(32, 16).astype("float32")
        yv = rng.randint(0, 4, size=(32, 1))
        (lv,) = exe.run(program, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)
        losses.append(float(lv[0]))
    return losses


def test_data_parallel_matches_single_device():
    import jax

    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    main, startup, loss = _build()
    single_scope = fluid.Scope()
    ref = _train(main, startup, loss, single_scope)

    main2, startup2, loss2 = _build()
    dp_scope = fluid.Scope()
    compiled = fluid.CompiledProgram(main2).with_data_parallel(loss_name=loss2.name)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup2, scope=dp_scope)
    rng = np.random.RandomState(0)
    dp_losses = []
    for _ in range(8):
        xv = rng.rand(32, 16).astype("float32")
        yv = rng.randint(0, 4, size=(32, 1))
        (lv,) = exe.run(compiled, feed={"x": xv, "y": yv}, fetch_list=[loss2], scope=dp_scope)
        dp_losses.append(float(lv[0]))
    # SPMD program computes the same global math => losses match closely
    np.testing.assert_allclose(dp_losses, ref, rtol=2e-4, atol=2e-5)


def test_tensor_parallel_sharding_hints():
    import jax

    main, startup, loss = _build()
    n_annot = fluid.parallel.shard_parameters(main, {r"fc_.*\.w_0": (None, "tp")})
    assert n_annot == 2
    mesh = fluid.parallel.make_mesh((4, 2), ("dp", "tp"))
    compiled = fluid.CompiledProgram(main).with_mesh(mesh)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    for _ in range(3):
        xv = rng.rand(32, 16).astype("float32")
        yv = rng.randint(0, 4, size=(32, 1))
        (lv,) = exe.run(compiled, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)
    assert np.isfinite(lv[0])
    # weight must actually be sharded over tp axis
    w = scope.find_var("fc_0.w_0")
    spec = w.sharding.spec
    assert tuple(spec) == (None, "tp"), spec


def test_dp_batch_not_divisible_replicates():
    main, startup, loss = _build()
    compiled = fluid.CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    xv = np.random.rand(6, 16).astype("float32")  # 6 % 8 != 0
    yv = np.random.randint(0, 4, size=(6, 1))
    (lv,) = exe.run(compiled, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)
    assert np.isfinite(lv[0])


def test_memory_optimize_remat_matches_baseline():
    """BuildStrategy.memory_optimize => jax.checkpoint over the forward:
    same losses, rematerialized backward (reference memory_optimize_pass
    capability, XLA-native form)."""
    import numpy as np

    def build():
        main, startup = fluid.Program(), fluid.Program()
        startup.random_seed = 21
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", [16], dtype="float32")
            y = fluid.layers.data("y", [1], dtype="float32")
            h = fluid.layers.fc(x, 64, act="tanh")
            h = fluid.layers.fc(h, 64, act="tanh")
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
            fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
        return main, startup, loss

    from paddle_tpu.parallel import make_mesh

    rng = np.random.RandomState(0)
    xv = rng.rand(8, 16).astype("f4")
    yv = xv.sum(1, keepdims=True).astype("f4")

    def run(memory_optimize):
        main, startup, loss = build()
        bs = fluid.BuildStrategy()
        bs.memory_optimize = memory_optimize
        mesh = make_mesh((8,), ("dp",))
        prog = fluid.CompiledProgram(main, build_strategy=bs).with_mesh(mesh)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)
        out = []
        for _ in range(5):
            (lv,) = exe.run(prog, feed={"x": xv, "y": yv}, fetch_list=[loss],
                            scope=scope)
            out.append(float(np.asarray(lv).reshape(-1)[0]))
        return out

    base = run(False)
    remat = run(True)
    np.testing.assert_allclose(remat, base, rtol=1e-6, atol=1e-7)
