"""DGC sparse gradient exchange (reference dgc_op.cc +
sparse_all_reduce_op_handle.cc semantics)."""
import numpy as np

import jax.numpy as jnp

from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.dgc import dgc_allreduce


def test_dgc_exchanges_topk_and_keeps_residual():
    W, D = 4, 32
    rng = np.random.RandomState(0)
    g = rng.randn(W, D).astype("f4")
    u = np.zeros((W, D), "f4")
    v = np.zeros((W, D), "f4")
    mesh = make_mesh((W,), ("dp",))
    sparsity = 0.75  # k = 8 of 32
    dense, u2, v2 = dgc_allreduce(jnp.asarray(g), jnp.asarray(u), jnp.asarray(v),
                                  mesh, sparsity=sparsity, momentum=0.9)
    dense, u2, v2 = map(np.asarray, (dense, u2, v2))

    k = 8
    # reference math: u=g (first step), select top-8 |u| per worker
    expected = np.zeros(D, "f4")
    for w in range(W):
        idx = np.argsort(-np.abs(g[w]))[:k]
        expected[idx] += g[w][idx]
        # residual keeps the rest
        rest = np.ones(D, bool)
        rest[idx] = False
        np.testing.assert_allclose(v2[w][rest], g[w][rest], atol=1e-6)
        np.testing.assert_allclose(v2[w][idx], 0.0, atol=1e-6)
    # every worker sees the identical summed sparse update
    for w in range(W):
        np.testing.assert_allclose(dense[w], expected, atol=1e-5)
    # momentum factor masking: sent coords restart their momentum
    for w in range(W):
        idx = np.argsort(-np.abs(g[w]))[:k]
        exp_u = g[w].copy()
        exp_u[idx] = 0.0
        np.testing.assert_allclose(u2[w], exp_u, atol=1e-6)


def test_dgc_multi_round_matches_numpy_reference():
    """Three rounds against a numpy port of the same DGC rule (momentum
    correction, error feedback, momentum factor masking)."""
    W, D, k = 2, 16, 2
    rng = np.random.RandomState(1)
    mesh = make_mesh((W,), ("dp",))
    u = jnp.zeros((W, D))
    v = jnp.zeros((W, D))
    u_ref = np.zeros((W, D), "f4")
    v_ref = np.zeros((W, D), "f4")
    for step in range(3):
        g = rng.randn(W, D).astype("f4")
        dense, u, v = dgc_allreduce(jnp.asarray(g), u, v, mesh,
                                    sparsity=1 - k / D, momentum=0.5)
        exp = np.zeros(D, "f4")
        for w in range(W):
            u_ref[w] = 0.5 * u_ref[w] + g[w]
            vacc = v_ref[w] + u_ref[w]
            idx = np.argsort(-np.abs(vacc))[:k]
            exp[idx] += vacc[idx]
            keep = np.ones(D, bool)
            keep[idx] = False
            v_ref[w] = np.where(keep, vacc, 0.0)
            u_ref[w] = np.where(keep, u_ref[w], 0.0)
        np.testing.assert_allclose(np.asarray(dense)[0], exp, atol=1e-5)
        np.testing.assert_allclose(np.asarray(v), v_ref, atol=1e-5)
        np.testing.assert_allclose(np.asarray(u), u_ref, atol=1e-5)
