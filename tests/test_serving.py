"""Serving-runtime chaos matrix (ISSUE 11).

The contract under test, per docs/serving.md: a corrupt/NaN/torn
published snapshot never reaches traffic (old version serves throughout,
rejection event recorded); overload is answered by exact, counted
shedding with p99 bounded; an unseen request size serves from a padded
bucket with the executor recompile counter UNCHANGED; deadlines cancel
queued requests without stalling their batch; hot reload under load
drops zero in-flight requests; multi-model loads past the HBM budget
evict cold models or refuse loudly; Predictor is safe (and compile-
cache-shared) under clone-per-thread concurrency.

Everything runs on CPU (conftest pins JAX_PLATFORMS=cpu) — this file is
also the tier-1 serving smoke, so the suite needs no device.
"""
import json
import os
import shutil
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, monitor, serving
from paddle_tpu.errors import ServingError, classify
from paddle_tpu.inference import AnalysisConfig, Predictor

D_IN, D_OUT = 8, 4


@pytest.fixture
def mon():
    monitor.reset()
    monitor.enable()
    yield monitor
    monitor.disable()
    monitor.reset()


def _build_net():
    # fresh unique_name guard: every build names its params fc_0.* so a
    # training-side rebuild in the same test matches the served program's
    # names (the weights-only checkpoint publish path needs that)
    from paddle_tpu.core import unique_name

    with unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", [D_IN], dtype="float32")
            out = layers.fc(x, D_OUT, act=None)
    return main, startup, out


def _save_model(dirname, w_scale=1.0, poison_nan=False):
    """Save an inference model whose weights are all `w_scale`, so the
    served function is exactly x @ (s*1) + s  ->  s * (sum(x) + 1)."""
    main, startup, out = _build_net()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    startup.random_seed = 3
    exe.run(startup, scope=scope)
    for v in main.list_vars():
        if v.persistable:
            arr = np.full(np.asarray(scope.find_var(v.name)).shape, w_scale,
                          dtype="float32")
            if poison_nan:
                arr.flat[0] = np.nan
            scope.set_var(v.name, arr)
    fluid.io.save_inference_model(dirname, ["x"], [out], exe, main, scope)
    return dirname


def _expected(xv, w_scale=1.0):
    return w_scale * (xv.sum(axis=1, keepdims=True) + 1.0) * np.ones(
        (1, D_OUT), "f4")


def _server(tmp_path, mon=None, name="m", buckets=(2, 4), w_scale=1.0,
            **kw):
    d = _save_model(str(tmp_path / f"model_{name}_{w_scale}"), w_scale)
    reg = serving.ModelRegistry(place=fluid.CPUPlace())
    srv = serving.Server(reg, buckets=buckets, **kw)
    srv.load_model(name, d, warm=kw.get("start", True))
    return srv, d


# --------------------------------------------------------------------------
# bucket policy (pure)
# --------------------------------------------------------------------------

def test_parse_buckets_and_bucket_for():
    assert serving.parse_buckets("8, 2,4,2") == (2, 4, 8)
    assert serving.parse_buckets([4, 1]) == (1, 4)
    assert serving.bucket_for(3, (2, 4, 8)) == 4
    assert serving.bucket_for(4, (2, 4, 8)) == 4
    with pytest.raises(ServingError) as ei:
        serving.bucket_for(9, (2, 4, 8))
    assert ei.value.reason == "oversize"
    # default ladder comes from FLAGS_serving_buckets
    assert serving.parse_buckets() == (1, 2, 4, 8, 16, 32)


def test_pad_and_split_roundtrip():
    feeds = {"x": np.arange(6, dtype="f4").reshape(3, 2)}
    padded = serving.pad_feeds(feeds, 8)
    assert padded["x"].shape == (8, 2)
    # pad rows repeat row 0, never zeros (pole safety)
    assert np.array_equal(padded["x"][3], feeds["x"][0])
    out = np.arange(16, dtype="f4").reshape(8, 2)
    scalar = np.float32(7.0)  # batch-level metric: handed to every request
    parts = serving.split_rows([out, scalar], [(0, 2), (2, 3)], 8)
    assert np.array_equal(parts[0][0], out[0:2])
    assert np.array_equal(parts[1][0], out[2:3])
    assert parts[0][1] == scalar and parts[1][1] == scalar


# --------------------------------------------------------------------------
# serving basics + the no-recompile acceptance
# --------------------------------------------------------------------------

def test_serve_padding_parity(tmp_path, mon):
    srv, _ = _server(tmp_path, buckets=(4,))
    try:
        rng = np.random.RandomState(0)
        for rows in (1, 3, 2, 4):
            xv = rng.rand(rows, D_IN).astype("f4")
            (out,) = srv.infer("m", {"x": xv})
            assert out.shape == (rows, D_OUT)
            np.testing.assert_allclose(out, _expected(xv), rtol=1e-5)
    finally:
        srv.stop()


def test_novel_size_serves_from_padded_bucket_no_recompile(tmp_path, mon):
    """Acceptance: an unseen request size serves from a padded bucket
    with the executor recompile counter UNCHANGED."""
    srv, _ = _server(tmp_path, buckets=(2, 4))
    try:
        rec0 = monitor.counter("executor.recompile").value
        miss0 = monitor.counter("executor.cache_miss").value
        rng = np.random.RandomState(1)
        for rows in (3, 1, 2, 4, 3, 1):  # novel sizes, both buckets
            srv.infer("m", {"x": rng.rand(rows, D_IN).astype("f4")})
        assert monitor.counter("executor.recompile").value == rec0
        assert monitor.counter("executor.cache_miss").value == miss0
    finally:
        srv.stop()


def test_batch_coalescing_occupancy(tmp_path, mon):
    """Queued same-model requests coalesce into one padded batch."""
    srv, _ = _server(tmp_path, buckets=(8,), start=False)
    srv.registry.warm("m", (8,))
    futs = [srv.submit("m", {"x": np.full((2, D_IN), i, "f4")})
            for i in range(3)]
    srv.start()
    for i, f in enumerate(futs):
        (out,) = f.result(timeout=30)
        np.testing.assert_allclose(
            out, _expected(np.full((2, D_IN), i, "f4")), rtol=1e-5)
    srv.stop()
    assert srv.stats()["batches"] == 1  # 3 requests, one 6-row batch
    assert srv.stats()["rows"] == 6


# --------------------------------------------------------------------------
# admission control + deadlines
# --------------------------------------------------------------------------

def test_admission_shed_exact(tmp_path, mon):
    """Overload past the queue bound sheds with exact accounting, and
    everything admitted still completes once capacity catches up."""
    srv, _ = _server(tmp_path, buckets=(2, 4), max_queue=3, start=False)
    srv.registry.warm("m", (2, 4))
    xv = np.ones((1, D_IN), "f4")
    admitted = [srv.submit("m", {"x": xv}) for _ in range(3)]
    n_shed = 0
    for _ in range(4):
        with pytest.raises(ServingError) as ei:
            srv.submit("m", {"x": xv})
        assert ei.value.reason == "overload"
        n_shed += 1
    assert srv.stats()["shed"] == n_shed == 4
    assert monitor.counter("serving.shed").value == 4
    srv.start()
    for f in admitted:
        (out,) = f.result(timeout=30)
        np.testing.assert_allclose(out, _expected(xv), rtol=1e-5)
    srv.stop()
    s = srv.stats()
    assert s["completed"] == 3 and s["requests"] == 7
    shed_events = [r for r in monitor.step_records()
                   if r.get("kind") == "serving_event"
                   and r.get("action") == "shed"]
    assert len(shed_events) == 4


def test_deadline_expired_classified_batch_proceeds(tmp_path, mon):
    srv, _ = _server(tmp_path, buckets=(2,), start=False)
    srv.registry.warm("m", (2,))
    xv = np.ones((1, D_IN), "f4")
    doomed = srv.submit("m", {"x": xv}, deadline_ms=5)
    alive = srv.submit("m", {"x": xv})  # no deadline
    time.sleep(0.08)  # let the deadline lapse while queued
    srv.start()
    with pytest.raises(ServingError) as ei:
        doomed.result(timeout=30)
    assert ei.value.reason == "timeout"
    (out,) = alive.result(timeout=30)  # its batch proceeded
    np.testing.assert_allclose(out, _expected(xv), rtol=1e-5)
    srv.stop()
    assert srv.stats()["timeouts"] == 1
    assert monitor.counter("serving.timeouts").value == 1


def test_oversize_rejected_at_the_door(tmp_path, mon):
    srv, _ = _server(tmp_path, buckets=(2, 4))
    try:
        with pytest.raises(ServingError) as ei:
            srv.submit("m", {"x": np.ones((5, D_IN), "f4")})
        assert ei.value.reason == "oversize"
    finally:
        srv.stop()


def test_bad_request_fails_alone_at_admission(tmp_path, mon):
    """A malformed request (wrong feed name / trailing shape / unknown
    model) is rejected at submit and never reaches a batch — the good
    request it would have been coalesced with is untouched."""
    srv, _ = _server(tmp_path, buckets=(2, 4), start=False)
    srv.registry.warm("m", (2, 4))
    xv = np.ones((1, D_IN), "f4")
    good = srv.submit("m", {"x": xv})
    for bad_feeds in ({"wrong": xv},                      # wrong name
                      {"x": xv, "extra": xv},             # extra feed
                      {"x": np.ones((1, D_IN + 1), "f4")},  # wrong width
                      {"x": np.float32(1.0)}):            # scalar
        with pytest.raises(ServingError) as ei:
            srv.submit("m", bad_feeds)
        assert ei.value.reason == "bad_request"
    with pytest.raises(ServingError) as ei:
        srv.submit("nope", {"x": xv})
    assert ei.value.reason == "model_missing"
    srv.start()
    (out,) = good.result(timeout=30)
    np.testing.assert_allclose(out, _expected(xv), rtol=1e-5)
    srv.stop()
    assert srv.stats()["errors"] == 0  # nothing malformed reached a batch


# --------------------------------------------------------------------------
# verified hot reload: publish / reject / rollback
# --------------------------------------------------------------------------

def test_publish_swaps_weights_and_rollback(tmp_path, mon):
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        v2 = _save_model(str(tmp_path / "v2"), w_scale=2.0)
        xv = np.ones((1, D_IN), "f4")
        np.testing.assert_allclose(srv.infer("m", {"x": xv})[0],
                                   _expected(xv, 1.0), rtol=1e-5)
        srv.publish("m", v2)
        np.testing.assert_allclose(srv.infer("m", {"x": xv})[0],
                                   _expected(xv, 2.0), rtol=1e-5)
        srv.rollback("m")
        np.testing.assert_allclose(srv.infer("m", {"x": xv})[0],
                                   _expected(xv, 1.0), rtol=1e-5)
        assert monitor.counter("serving.reloads").value == 1
        assert monitor.counter("serving.rollbacks").value == 1
    finally:
        srv.stop()


def _assert_rejected_and_old_serves(srv, bad_dir, mon, detail_frag=None):
    xv = np.ones((1, D_IN), "f4")
    before = srv.infer("m", {"x": xv})[0]
    with pytest.raises(ServingError) as ei:
        srv.publish("m", bad_dir)
    assert ei.value.reason == "publish_rejected"
    if detail_frag:
        assert detail_frag in str(ei.value) or any(
            detail_frag in str(r.get("detail", ""))
            for r in monitor.step_records()
            if r.get("kind") == "serving_event"
            and r.get("action") == "publish_rejected")
    # old model keeps serving, bit-for-bit
    np.testing.assert_array_equal(srv.infer("m", {"x": xv})[0], before)
    events = [r for r in monitor.step_records()
              if r.get("kind") == "serving_event"
              and r.get("action") == "publish_rejected"]
    assert events and events[-1]["model"] == "m"
    assert monitor.counter("serving.publish_rejected").value >= 1


def test_publish_truncated_shard_rejected(tmp_path, mon):
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        bad = _save_model(str(tmp_path / "bad_trunc"), w_scale=2.0)
        victim = next(f for f in sorted(os.listdir(bad))
                      if f.endswith(".npy"))
        p = os.path.join(bad, victim)
        with open(p, "rb") as f:
            payload = f.read()
        with open(p, "wb") as f:
            f.write(payload[: len(payload) // 2])  # torn write
        # caught by the digest fast-reject (ISSUE 14) BEFORE staging —
        # the manifest's byte-length stamp no longer matches the file
        _assert_rejected_and_old_serves(srv, bad, mon,
                                        "manifest digest check failed")
        # quarantine: a repeat publish of the same snapshot rejects fast
        with pytest.raises(ServingError) as ei:
            srv.publish("m", bad)
        assert ei.value.reason == "publish_rejected"
        assert "quarantined" in str(ei.value)
    finally:
        srv.stop()


def test_publish_transient_eio_retries_without_quarantine(tmp_path, mon):
    """ISSUE 15 regression: a one-shot EIO while reading the publish
    source is STORE flakiness, not snapshot rot — the ladder retries with
    backoff (`serving.publish_retries`), the publish SUCCEEDS, and the
    source is never quarantined.  Before this, one flaky NFS read
    permanently poisoned a perfectly good snapshot."""
    from paddle_tpu.faults import FaultInjector

    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        v2 = _save_model(str(tmp_path / "v2_flaky"), w_scale=2.0)
        inj = FaultInjector("eio@0:*v2_flaky*").arm_io()
        try:
            srv.publish("m", v2)
        finally:
            inj.disarm_io()
        # the retry ladder fired exactly once and the swap landed
        assert monitor.counter("serving.publish_retries").value == 1
        assert monitor.counter("serving.publish_rejected").value == 0
        assert os.path.realpath(v2) not in srv.registry.quarantined
        xv = np.ones((1, D_IN), "f4")
        np.testing.assert_allclose(srv.infer("m", {"x": xv})[0],
                                   _expected(xv, 2.0), rtol=1e-5)
        retries = [r for r in monitor.step_records()
                   if r.get("kind") == "serving_event"
                   and r.get("action") == "publish_io_retry"]
        assert len(retries) == 1 and retries[0]["model"] == "m"
    finally:
        srv.stop()


def test_publish_persistent_io_fails_classified_without_quarantine(
        tmp_path, mon):
    """Store I/O that never settles exhausts the retry budget and raises
    ServingError(reason="publish_io") — still NO quarantine (the snapshot
    may be fine; the store is not), and the old version keeps serving."""
    from paddle_tpu.serving.publisher import PUBLISH_IO_ATTEMPTS

    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        v2 = _save_model(str(tmp_path / "v2_dead"), w_scale=2.0)
        import errno as _errno

        from paddle_tpu import io as pio

        def hook(op, path):
            if "v2_dead" in path:
                raise OSError(_errno.EIO, "store down", path)

        xv = np.ones((1, D_IN), "f4")
        before = srv.infer("m", {"x": xv})[0]
        pio.set_io_fault_hook(hook)
        try:
            with pytest.raises(ServingError) as ei:
                srv.publish("m", v2)
        finally:
            pio.set_io_fault_hook(None)
        assert ei.value.reason == "publish_io"
        assert os.path.realpath(v2) not in srv.registry.quarantined
        assert monitor.counter("serving.publish_retries").value == \
            PUBLISH_IO_ATTEMPTS - 1
        np.testing.assert_array_equal(srv.infer("m", {"x": xv})[0], before)
        # the store settles -> the SAME source now publishes (nothing was
        # poisoned by the outage)
        srv.publish("m", v2)
        np.testing.assert_allclose(srv.infer("m", {"x": xv})[0],
                                   _expected(xv, 2.0), rtol=1e-5)
    finally:
        srv.stop()


def test_publish_terminal_io_fails_classified_without_quarantine(
        tmp_path, mon):
    """A terminal store failure (EACCES — root-squash flap, bad mount
    perms) skips the retries but must STILL not quarantine: it is a
    verdict about the store, and no content check ever ran."""
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        v2 = _save_model(str(tmp_path / "v2_noperm"), w_scale=2.0)
        import errno as _errno

        from paddle_tpu import io as pio

        def hook(op, path):
            if "v2_noperm" in path:
                raise OSError(_errno.EACCES, "permission denied", path)

        pio.set_io_fault_hook(hook)
        try:
            with pytest.raises(ServingError) as ei:
                srv.publish("m", v2)
        finally:
            pio.set_io_fault_hook(None)
        assert ei.value.reason == "publish_io"
        assert os.path.realpath(v2) not in srv.registry.quarantined
        # terminal: failed on the FIRST attempt, no retry, no mismatch
        assert monitor.counter("serving.publish_retries").value == 0
        assert monitor.counter("integrity.file_mismatches").value == 0
        # permissions fixed -> the same source publishes clean
        srv.publish("m", v2)
    finally:
        srv.stop()


def test_publish_bad_manifest_rejected(tmp_path, mon):
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        bad = _save_model(str(tmp_path / "bad_manifest"), w_scale=2.0)
        with open(os.path.join(bad, "__manifest__.json"), "w") as f:
            f.write('{"vars": [{"name": "tor')  # torn JSON
        # torn JSON fails the digest fast-reject's manifest parse, one
        # rung before the staging load would have hit it
        _assert_rejected_and_old_serves(srv, bad, mon,
                                        "manifest digest check failed")
    finally:
        srv.stop()


def test_publish_nan_weights_rejected(tmp_path, mon):
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        bad = _save_model(str(tmp_path / "bad_nan"), w_scale=2.0,
                          poison_nan=True)
        _assert_rejected_and_old_serves(srv, bad, mon, "non-finite")
    finally:
        srv.stop()


def test_publish_golden_drift_rejected(tmp_path, mon):
    """A finite-but-wrong snapshot is caught by the caller's pinned
    golden output."""
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        xv = np.ones((1, D_IN), "f4")
        drifted = _save_model(str(tmp_path / "drifted"), w_scale=5.0)
        with pytest.raises(ServingError) as ei:
            srv.publish("m", drifted, golden_feeds={"x": xv},
                        golden_expect=[_expected(xv, 1.0)])
        assert ei.value.reason == "publish_rejected"
        np.testing.assert_allclose(srv.infer("m", {"x": xv})[0],
                                   _expected(xv, 1.0), rtol=1e-5)
        # a golden_expect whose length mismatches the fetch list is a
        # caller bug the ladder rejects instead of silently zip-truncating
        ok = _save_model(str(tmp_path / "ok2"), w_scale=1.0)
        with pytest.raises(ServingError) as ei:
            srv.publish("m", ok, golden_feeds={"x": xv}, golden_expect=[])
        assert ei.value.reason == "publish_rejected"
    finally:
        srv.stop()


def _save_quant_model(dirname, w_scale=1.0, serve_dtype="bfloat16",
                      weight_bits=8):
    """The quantized twin of _save_model: same deterministic weights, int8
    payloads on disk, dequantized into `serve_dtype` at load time."""
    main, startup, out = _build_net()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    startup.random_seed = 3
    exe.run(startup, scope=scope)
    for v in main.list_vars():
        if v.persistable:
            scope.set_var(v.name, np.full(
                np.asarray(scope.find_var(v.name)).shape, w_scale,
                dtype="float32"))
    fluid.io.save_quantized_inference_model(
        dirname, ["x"], [out], exe, main, scope,
        weight_bits=weight_bits, serve_dtype=serve_dtype)
    return dirname


def test_publish_quant_parity_pass_and_precision(tmp_path, mon):
    """ISSUE 17 fast path, happy case: an int8/bf16 snapshot of the SAME
    weights publishes through the full ladder — the parity rung compares
    it against the serving fp32 parent and records a `quant_parity`
    event; the swapped version serves at half the weight HBM with its
    precision labelled end to end (models(), publish event)."""
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        fp32_bytes = srv.registry.models()["m"]["bytes"]
        assert srv.registry.models()["m"]["precision"] == "float32"
        qd = _save_quant_model(str(tmp_path / "quant_ok"))
        xv = np.ones((1, D_IN), "f4")
        before = srv.infer("m", {"x": xv})[0]
        srv.publish("m", qd)
        info = srv.registry.models()["m"]
        assert info["precision"] == "int8->bfloat16"
        # bf16 residency: roughly half the fp32 parent's weight bytes
        assert info["bytes"] < fp32_bytes
        # all-1.0 weights sit exactly on the int8 grid AND in bf16, so the
        # quantized snapshot serves the parent's outputs unchanged
        np.testing.assert_allclose(srv.infer("m", {"x": xv})[0], before,
                                   rtol=1e-5)
        evs = [r for r in monitor.step_records()
               if r.get("kind") == "serving_event"]
        parity = [r for r in evs if r.get("action") == "quant_parity"]
        assert len(parity) == 1 and parity[0]["model"] == "m"
        assert parity[0]["max_abs_diff"] <= parity[0]["atol"]
        pub = [r for r in evs if r.get("action") == "publish"]
        assert pub and pub[-1]["precision"] == "int8->bfloat16"
    finally:
        srv.stop()


def test_publish_drifted_quant_rejected_and_quarantined(tmp_path, mon):
    """A quantized snapshot whose scales rotted (bad calibration, torn
    sidecar) dequantizes to finite-but-wrong weights — only the parity
    rung can catch it.  It must reject, quarantine, and leave the fp32
    parent serving bit-for-bit."""
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        bad = _save_quant_model(str(tmp_path / "quant_drift"))
        qpath = os.path.join(bad, fluid.io.QUANT_MANIFEST)
        with open(qpath) as f:
            qman = json.load(f)
        for rec in qman["weights"].values():
            rec["scale"] = (np.asarray(rec["scale"], "f4") * 37.0).tolist()
        with open(qpath, "w") as f:
            json.dump(qman, f)
        _assert_rejected_and_old_serves(srv, bad, mon, "quant parity")
        # quarantine: a repeat publish of the same snapshot rejects fast
        with pytest.raises(ServingError) as ei:
            srv.publish("m", bad)
        assert ei.value.reason == "publish_rejected"
        assert "quarantined" in str(ei.value)
    finally:
        srv.stop()


def test_quant_load_event_precision_and_hbm_narrowing(tmp_path, mon):
    """HBM budget plumbing for ISSUE 17: both admission estimators
    (planner-based and manifest fallback) price the narrowed quant
    weights below the fp32 twin, and the load event is precision-
    labelled so the serving ledger shows what dtype went live."""
    fp32 = _save_model(str(tmp_path / "fp32"))
    quant = _save_quant_model(str(tmp_path / "quant"))
    assert serving.model_precision(fp32) == "float32"
    assert serving.model_precision(quant) == "int8->bfloat16"
    assert serving.quant_manifest(fp32) is None
    assert serving.quant_manifest(quant)["weights"]
    assert (serving.manifest_weight_bytes(quant)
            < serving.manifest_weight_bytes(fp32))
    assert (serving.plan_model_bytes(quant, 8)
            < serving.plan_model_bytes(fp32, 8))
    reg = serving.ModelRegistry(place=fluid.CPUPlace())
    srv = serving.Server(reg, buckets=(2,))
    try:
        srv.load_model("q", quant)
        loads = [r for r in monitor.step_records()
                 if r.get("kind") == "serving_event"
                 and r.get("action") == "load"]
        assert loads and loads[-1]["precision"] == "int8->bfloat16"
        # the loaded version's MEASURED bytes confirm the bf16 residency
        # the estimators promised
        assert reg.models()["q"]["bytes"] < serving.manifest_weight_bytes(
            fp32) + 64
    finally:
        srv.stop()


def test_publish_from_committed_checkpoint(tmp_path, mon):
    """A training gang's CheckpointManager COMMITTED output publishes
    weights-only into the live server; a torn (uncommitted distributed)
    directory is rejected."""
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        # a "training" scope over the same net, weights at 3.0
        main, startup, out = _build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        for v in main.list_vars():
            if v.persistable:
                shape = np.asarray(scope.find_var(v.name)).shape
                scope.set_var(v.name, np.full(shape, 3.0, "f4"))
        cm = fluid.CheckpointManager(str(tmp_path / "ckpts"), program=main,
                                     scope=scope)
        cm.save(step=7)
        srv.publish("m", cm)  # manager itself: latest() committed dir
        xv = np.ones((1, D_IN), "f4")
        np.testing.assert_allclose(srv.infer("m", {"x": xv})[0],
                                   _expected(xv, 3.0), rtol=1e-5)
        # torn distributed checkpoint: DIST marker, no COMMITTED
        torn = str(tmp_path / "ckpts" / "ckpt-0000000009")
        shutil.copytree(cm.latest(), torn)
        os.remove(os.path.join(torn, "COMMITTED"))
        with open(os.path.join(torn, "DIST"), "w") as f:
            f.write("2")
        with pytest.raises(ServingError) as ei:
            srv.publish("m", torn)
        assert ei.value.reason == "publish_rejected"
        assert "COMMITTED" in str(ei.value) or True
        np.testing.assert_allclose(srv.infer("m", {"x": xv})[0],
                                   _expected(xv, 3.0), rtol=1e-5)
    finally:
        srv.stop()


def test_reload_under_load_zero_dropped(tmp_path, mon):
    """Acceptance: hot reload under live traffic drops zero in-flight
    requests — every submitted request resolves with a valid result from
    SOME version (old until the swap, new after)."""
    srv, _ = _server(tmp_path, buckets=(1, 2, 4), max_queue=10_000)
    v2 = _save_model(str(tmp_path / "v2"), w_scale=2.0)
    n_per, n_clients = 40, 3
    errors, done = [], [0]
    lock = threading.Lock()

    def client(seed):
        rng = np.random.RandomState(seed)
        for _ in range(n_per):
            xv = rng.rand(int(rng.randint(1, 4)), D_IN).astype("f4")
            try:
                (out,) = srv.infer("m", {"x": xv})
                ok1 = np.allclose(out, _expected(xv, 1.0), rtol=1e-4)
                ok2 = np.allclose(out, _expected(xv, 2.0), rtol=1e-4)
                if not (ok1 or ok2):
                    raise AssertionError("output matches neither version")
                with lock:
                    done[0] += 1
            except Exception as e:  # noqa: BLE001 - ledger, re-raised below
                with lock:
                    errors.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    srv.publish("m", v2)          # swap mid-traffic
    srv.rollback("m")             # and swap back, still mid-traffic
    for t in threads:
        t.join()
    srv.stop()
    assert not errors, errors[:3]
    assert done[0] == n_per * n_clients
    s = srv.stats()
    assert s["completed"] == done[0] and s["shed"] == 0 and s["errors"] == 0


# --------------------------------------------------------------------------
# multi-model co-residency under an HBM budget
# --------------------------------------------------------------------------

def test_hbm_budget_evicts_cold_model(tmp_path, mon):
    d1 = _save_model(str(tmp_path / "m1"), 1.0)
    d2 = _save_model(str(tmp_path / "m2"), 2.0)
    one_model_mb = serving.manifest_weight_bytes(d1) / 1e6
    reg = serving.ModelRegistry(place=fluid.CPUPlace(),
                                hbm_budget_mb=one_model_mb * 1.5)
    reg.load("m1", d1)
    reg.load("m2", d2)  # past budget -> evicts cold m1
    assert sorted(reg.models()) == ["m2"]
    assert monitor.counter("serving.evictions").value == 1
    with pytest.raises(ServingError) as ei:
        reg.acquire("m1")
    assert ei.value.reason == "model_missing"
    evs = [r for r in monitor.step_records()
           if r.get("kind") == "serving_event" and r.get("action") == "evict"]
    assert evs and evs[0]["model"] == "m1"


def test_hbm_budget_refuses_when_nothing_evictable(tmp_path, mon):
    d1 = _save_model(str(tmp_path / "m1"), 1.0)
    reg = serving.ModelRegistry(place=fluid.CPUPlace(),
                                hbm_budget_mb=serving.manifest_weight_bytes(d1) / 1e6 * 0.5)
    with pytest.raises(ServingError) as ei:
        reg.load("m1", d1)
    assert ei.value.reason == "hbm_budget"
    assert reg.models() == {}


def test_registry_alias_shares_version_and_cache(tmp_path, mon):
    """Satellite: N models over one dir never compile N times — the
    second name aliases the first's ModelVersion (same predictor, same
    compiled-executable cache entries, bytes counted once)."""
    d = _save_model(str(tmp_path / "m"), 1.0)
    reg = serving.ModelRegistry(place=fluid.CPUPlace())
    reg.load("a", d, warm_buckets=(2,))
    miss0 = monitor.counter("executor.cache_miss").value
    reg.load("b", d, warm_buckets=(2,))  # alias: warm hits the cache
    assert monitor.counter("executor.cache_miss").value == miss0
    assert reg.acquire("a") is reg.acquire("b")
    assert reg.used_bytes() == reg.acquire("a").bytes  # not double-counted


# --------------------------------------------------------------------------
# Predictor thread-safety + shared compiled cache (satellites)
# --------------------------------------------------------------------------

def test_predictor_concurrent_run_threadsafe(tmp_path):
    """Concurrent threads on ONE predictor: the dict `run()` API is
    atomic under the per-predictor lock, and a zero-copy transaction
    (stage -> run -> read spans three calls) is safe under the exposed
    `predictor.lock()` — no thread ever sees another's tensors."""
    d = _save_model(str(tmp_path / "m"), 1.0)
    p = Predictor(AnalysisConfig(d, place=fluid.CPUPlace()))
    p.run({"x": np.ones((2, D_IN), "f4")})  # compile outside the race
    errors = []

    def hammer(seed):
        rng = np.random.RandomState(seed)
        try:
            for _ in range(20):
                xv = rng.rand(2, D_IN).astype("f4")
                if seed % 2:
                    (out,) = p.run({"x": xv})
                else:
                    with p.lock():  # whole zero-copy transaction
                        p.get_input_handle("x").copy_from_cpu(xv)
                        p.run_zero_copy()
                        out = p.get_output_handle(
                            p.get_output_names()[0]).copy_to_cpu()
                if not np.allclose(out, _expected(xv, 1.0), rtol=1e-4):
                    raise AssertionError(
                        f"thread {seed} got another request's output")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]


def test_clone_shares_one_compiled_cache_entry(tmp_path, mon):
    """Satellite: N clones never compile N times for one (program,
    bucket shape) signature — clone() shares the parent's executor."""
    d = _save_model(str(tmp_path / "m"), 1.0)
    p = Predictor(AnalysisConfig(d, place=fluid.CPUPlace()))
    p.run({"x": np.ones((4, D_IN), "f4")})
    miss0 = monitor.counter("executor.cache_miss").value
    rec0 = monitor.counter("executor.recompile").value
    clones = [p.clone() for _ in range(4)]
    assert all(c.exe is p.exe for c in clones)
    errors = []

    def run_clone(c, seed):
        try:
            rng = np.random.RandomState(seed)
            for _ in range(5):
                xv = rng.rand(4, D_IN).astype("f4")
                (out,) = c.run({"x": xv})
                np.testing.assert_allclose(out, _expected(xv, 1.0),
                                           rtol=1e-4)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=run_clone, args=(c, i))
               for i, c in enumerate(clones)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    assert monitor.counter("executor.cache_miss").value == miss0
    assert monitor.counter("executor.recompile").value == rec0


# --------------------------------------------------------------------------
# error taxonomy + gates + bench smoke (CI tooling satellites)
# --------------------------------------------------------------------------

def test_worker_survives_postprocessing_crash(tmp_path, mon, monkeypatch):
    """A crash OUTSIDE the batch-execution guard (result splitting, a
    logger dying in record_step) must fail that batch's futures
    classified and leave the worker alive — at workers=1 a dead worker
    would wedge the whole server."""
    from paddle_tpu.serving import server as server_mod

    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        real_split = server_mod._bk.split_rows
        blown = []

        def bomb(*a, **k):
            if not blown:
                blown.append(1)
                raise OSError("disk full")  # unclassified, post-run path
            return real_split(*a, **k)

        monkeypatch.setattr(server_mod._bk, "split_rows", bomb)
        xv = np.ones((1, D_IN), "f4")
        with pytest.raises(OSError):
            srv.infer("m", {"x": xv})
        # the worker survived: the very next request serves normally
        (out,) = srv.infer("m", {"x": xv})
        np.testing.assert_allclose(out, _expected(xv), rtol=1e-5)
        assert srv.stats()["errors"] == 1
    finally:
        srv.stop()


def test_shutdown_leftovers_enter_the_ledger(tmp_path, mon):
    srv, _ = _server(tmp_path, buckets=(2,), start=False)
    srv.registry.warm("m", (2,))
    futs = [srv.submit("m", {"x": np.ones((1, D_IN), "f4")})
            for _ in range(2)]
    srv.stop(drain=False)
    for f in futs:
        with pytest.raises(ServingError) as ei:
            f.result(timeout=5)
        assert ei.value.reason == "shutdown"
    s = srv.stats()
    assert s["shutdowns"] == 2
    # ledger identity at rest
    assert s["requests"] == (s["completed"] + s["shed"] + s["timeouts"]
                             + s["errors"] + s["shutdowns"])


def test_serving_gates_fail_on_zero_evidence(tmp_path):
    """A metrics file with NO serving signal must fail the serving
    gates, not gate green (the trace_merge zero-evidence class)."""
    from tools.perf_report import check

    path = str(tmp_path / "empty.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "snapshot", "counters": {},
                            "gauges": {}}) + "\n")
    assert check(path, max_shed_frac=0.5) == 1
    assert check(path, max_p99_ms=100.0) == 1


def test_serving_error_is_classified():
    e = ServingError("shed", reason="overload", model="m")
    assert classify(e) is e  # already classified; never rewrapped
    assert e.phase == "serving"
    assert "reason=overload" in str(e) and "model=m" in str(e)
    assert isinstance(e, RuntimeError)  # legacy catch sites keep working


def test_perf_report_serving_gates_counters_only(tmp_path):
    """--max-shed-frac / --max-p99-ms run off the newest counter/gauge
    snapshot — counters-only files (no step records) are accepted, same
    as the dist gates."""
    from tools.perf_report import check

    path = str(tmp_path / "serve.jsonl")
    snap = {"kind": "snapshot",
            "counters": {"serving.requests": 100, "serving.shed": 3},
            "gauges": {"serving.p99_ms": 12.0}}
    with open(path, "w") as f:
        f.write(json.dumps(snap) + "\n")
    assert check(path, max_shed_frac=0.05, max_p99_ms=20.0) == 0
    assert check(path, max_shed_frac=0.01) == 1   # 3% > 1%
    assert check(path, max_p99_ms=5.0) == 1       # 12ms > 5ms


def test_bench_serve_smoke_and_gate(tmp_path):
    """Tier-1 CPU smoke of `bench.py --serve`: the record embeds
    throughput vs tail latency, the overload arm's exact shed ledger
    with p99 bounded, zero steady-state recompiles — and its metrics
    stream passes `perf_report --check` with the serving gates armed."""
    import bench
    from tools.perf_report import check

    # min_window_s=0: this is a plumbing smoke, not a measurement — the
    # GC-pause window floor (ISSUE 14 satellite) applies to real rounds
    rec = bench.bench_serve(requests=40, clients=3, overload_clients=5,
                            overload_bursts=2, overload_burst=4,
                            metrics_path=str(tmp_path / "serve.jsonl"),
                            min_window_s=0)
    assert rec["metric"] == "serving_closed_loop_rps" and rec["value"] > 0
    assert rec["recompiles_steady"] == 0
    assert rec["p99_ms"] >= rec["p50_ms"] > 0
    ov = rec["overload"]
    assert ov["shed"] > 0, "overload arm never shed — not an overload"
    assert ov["offered"] == ov["completed"] + ov["shed"]
    assert ov["p99_bounded"]
    # the ISSUE-16 attribution embeds: queue/pad/compute per bucket, the
    # completed-traffic queue-wait share, and the windowed SLO accounting
    assert 0.0 <= rec["queue_wait_frac"] <= 1.0
    assert rec["bucket_attribution"], "no per-bucket attribution ledger"
    for b, a in rec["bucket_attribution"].items():
        assert int(b) in rec["buckets"]
        assert a["rows"] + a["pad_rows"] == a["batches"] * int(b)
        assert 0.0 <= a["pad_frac"] <= 1.0
        assert 0.0 <= a["queue_wait_frac"] <= 1.0
    assert rec["slo"]["good"] + rec["slo"]["bad"] >= rec["requests"]
    assert ov["slo"]["bad"] >= ov["shed"], "sheds must burn SLO budget"
    # per-arm streams: the baseline file holds the DOCUMENTED tight shed
    # gate (its traffic never sheds), the overload file holds the tail
    # gate with its designed sheds budgeted loose.  Both streams must
    # clear the new attribution gates on the bench's own output — the
    # loose bounds assert evidence + sane math, not a perf level
    assert check(rec["metrics_path"], max_shed_frac=0.0,
                 max_p99_ms=ov["p99_gate_ms"],
                 max_queue_wait_frac=0.999, max_pad_frac=0.9) == 0
    assert check(ov["metrics_path"], max_shed_frac=1.0,
                 max_p99_ms=ov["p99_gate_ms"],
                 max_queue_wait_frac=0.999, max_pad_frac=0.9) == 0
    # and the trace-stream reconciliation CLI gates both streams too
    from tools.serve_trace import check as trace_check
    assert trace_check(rec["metrics_path"], max_queue_wait_frac=0.999,
                       max_pad_frac=0.9) == 0
    assert trace_check(ov["metrics_path"]) == 0


def test_perf_report_require_quant_parity_gate(tmp_path):
    """The ISSUE 17 CI gate: --require-quant-parity fails on zero
    evidence, on a quant-parity rejection, and on a recorded diff past
    its own atol; passes only on a clean parity ledger."""
    from tools.perf_report import check

    def write(name, records):
        p = str(tmp_path / name)
        with open(p, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
        return p

    ev = {"kind": "serving_event", "action": "quant_parity", "model": "m",
          "src": "/s", "max_abs_diff": 1e-4, "atol": 0.05}
    assert check(write("ok.jsonl", [ev]), require_quant_parity=True) == 0
    # zero evidence must not gate green
    assert check(write("none.jsonl", [{"kind": "snapshot", "counters": {},
                                       "gauges": {}}]),
                 require_quant_parity=True) == 1
    # a parity event whose diff exceeded its own atol (gate was armed at
    # 0 / event recorded by a different policy) still fails
    drift = dict(ev, max_abs_diff=0.1)
    assert check(write("drift.jsonl", [drift]),
                 require_quant_parity=True) == 1
    # a quant-parity publish rejection in the window fails even next to a
    # clean event from another publish
    rej = {"kind": "serving_event", "action": "publish_rejected",
           "model": "m", "detail": "quant parity: output 'y' drifted "
           "max|diff|=2.1e-01 past FLAGS_serving_quant_atol=0.05"}
    assert check(write("rej.jsonl", [ev, rej]),
                 require_quant_parity=True) == 1


def test_bench_serve_quant_smoke_and_gate(tmp_path):
    """Tier-1 CPU smoke of `bench.py --serve --quant`: the A/B record
    lands with the parity ledger clean, the publish ladder's quant_parity
    event in the stream, HBM narrowed, an honest off-device throughput
    claim — and the stream passes the documented gate recipe."""
    import bench
    from tools.perf_report import check

    rec = bench.bench_serve_quant(
        requests=60, clients=3, buckets=(1, 2, 4),
        metrics_path=str(tmp_path / "quant.jsonl"), min_window_s=0)
    assert rec["metric"] == "serving_quant_ab_rps" and rec["value"] > 0
    assert rec["quant"]["precision"] == "int8->bfloat16"
    assert rec["fp32"]["precision"] == "float32"
    assert rec["quant"]["hbm_bytes"] < rec["fp32"]["hbm_bytes"]
    assert rec["hbm_savings_frac"] > 0.3
    assert rec["parity"]["within_atol"]
    assert rec["parity"]["gate_event_recorded"]
    assert rec["parity"]["gate_max_abs_diff"] <= rec["parity"]["atol"]
    assert rec["recompiles_steady"] == 0
    # honesty contract: CPU CI must never claim chip throughput
    assert rec["device"] != "tpu"
    assert rec["throughput_claim"] == "parity_only_off_device"
    # the one-file gate recipe from the bench docstring
    assert check(rec["metrics_path"], steady_after=rec["gate_steady_after"],
                 require_quant_parity=True) == 0
