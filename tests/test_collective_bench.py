"""ICI collective microbench harness validation on the virtual 8-dev mesh
(BASELINE.md last row's harness — methodology ready for real multi-chip).
"""
import jax

import tools.collective_bench as cb


def test_collective_bench_all_kinds_run():
    mesh = cb._mesh(8)
    for kind in ("allreduce", "all_gather", "reduce_scatter", "ppermute"):
        rec = cb.bench_collective(kind, 0.1, mesh, iters=1, chain=2)
        assert rec["devices"] == 8
        assert rec["time_us"] > 0
        assert rec["achieved_gbps"] >= 0


def test_collective_bench_algo_bytes_formulas():
    # allreduce algorithmic bytes = 2(n-1)/n * payload; gather/scatter =
    # (n-1)/n; ppermute = payload.  Pin via one synthetic record each.
    mesh = cb._mesh(8)
    r_ar = cb.bench_collective("allreduce", 0.1, mesh, iters=1, chain=2)
    r_pp = cb.bench_collective("ppermute", 0.1, mesh, iters=1, chain=2)
    # same payload: achieved_gbps ratio reflects the algo-bytes ratio up to
    # timing noise; just assert both computed on the same payload size
    assert abs(r_ar["payload_mb"] - r_pp["payload_mb"]) < 1e-6
