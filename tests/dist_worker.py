"""Worker process for test_dist_multiprocess (reference:
test_dist_base.py:47 TestDistRunnerBase — trains RUN_STEP steps and
pickles per-step losses for the parent to compare)."""
import json
import os
import sys

# must be set before jax import
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=2").strip()

import numpy as np  # noqa: E402


def build_model():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 90
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [32], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="int64")
        h = fluid.layers.fc(x, 64, act="relu")
        logits = fluid.layers.fc(h, 10)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    return main, startup, loss


def main():
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.parallel import distributed as dist

    run_local = os.environ.get("RUN_LOCAL") == "1"
    if not run_local:
        dist.init_distributed()  # PADDLE_TRAINER_* env contract
        tid = dist.trainer_id()
        nproc = dist.num_trainers()
    else:
        tid, nproc = 0, 1

    mesh = dist.global_mesh()
    n_dev = len(jax.devices())

    prog, startup, loss = build_model()
    compiled = fluid.CompiledProgram(prog).with_mesh(mesh)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(1234)  # same global stream in every worker
    per = 32 // nproc
    losses = []
    for step in range(5):
        xg = rng.rand(32, 32).astype("f4")
        yg = rng.randint(0, 10, size=(32, 1)).astype("int64")
        xl = xg[tid * per:(tid + 1) * per]
        yl = yg[tid * per:(tid + 1) * per]
        (lv,) = exe.run(compiled, feed={"x": xl, "y": yl},
                        fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    print("LOSSES " + json.dumps({"trainer": tid, "n_dev": n_dev,
                                  "losses": losses}), flush=True)


if __name__ == "__main__":
    main()
