"""LoD/sequence subsystem: masking correctness vs numpy references.

Reference test pattern: per-op numpy golden (unittests/test_sequence_*.py
compute expected outputs by walking LoD offsets on flat tensors; here the
goldens walk the ragged lists directly)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.lod import LoDTensor, bucket_length


def run_seq(build, seqs, extra_feed=None, fetch=None):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        feat = seqs[0].shape[1:]
        x = layers.data("x", list(feat), dtype=str(seqs[0].dtype), lod_level=1)
        outs = build(x)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    feed = {"x": LoDTensor(seqs)}
    feed.update(extra_feed or {})
    fetch = fetch or outs
    fetch = fetch if isinstance(fetch, (list, tuple)) else [fetch]
    return exe.run(main, feed=feed, fetch_list=list(fetch))


def ragged(lengths, feat=(3,), seed=0, dtype="float32"):
    rng = np.random.RandomState(seed)
    return [rng.randn(l, *feat).astype(dtype) for l in lengths]


class TestSequencePool:
    @pytest.mark.parametrize("ptype", ["average", "sum", "sqrt", "max", "last", "first"])
    def test_golden(self, ptype):
        seqs = ragged([3, 5, 1, 4])
        (out,) = run_seq(lambda x: layers.sequence_pool(x, ptype), seqs)
        for i, s in enumerate(seqs):
            if ptype == "average":
                exp = s.mean(0)
            elif ptype == "sum":
                exp = s.sum(0)
            elif ptype == "sqrt":
                exp = s.sum(0) / np.sqrt(len(s))
            elif ptype == "max":
                exp = s.max(0)
            elif ptype == "last":
                exp = s[-1]
            else:
                exp = s[0]
            np.testing.assert_allclose(out[i], exp, rtol=1e-5, atol=1e-5)


class TestSequenceSoftmax:
    def test_masked(self):
        seqs = ragged([2, 6, 4], feat=(1,))
        (out,) = run_seq(layers.sequence_softmax, seqs)
        for i, s in enumerate(seqs):
            e = np.exp(s - s.max())
            np.testing.assert_allclose(out[i, : len(s)], e / e.sum(), rtol=1e-5, atol=1e-6)
            assert np.all(out[i, len(s):] == 0)


class TestSequenceExpand:
    def test_broadcast_rows(self):
        seqs = ragged([2, 5], feat=(4,))
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            y = layers.data("y", [4], dtype="float32", lod_level=1)
            xv = layers.data("xv", [4], dtype="float32")
            out = layers.sequence_expand(xv, y)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        xrow = np.arange(8, dtype="float32").reshape(2, 4)
        (o,) = exe.run(main, feed={"y": LoDTensor(seqs), "xv": xrow}, fetch_list=[out])
        for i, s in enumerate(seqs):
            assert np.all(o[i, : len(s)] == xrow[i])
            assert np.all(o[i, len(s):] == 0)


class TestSequenceReverse:
    def test_golden(self):
        seqs = ragged([3, 1, 5])
        (out,) = run_seq(layers.sequence_reverse, seqs)
        for i, s in enumerate(seqs):
            np.testing.assert_allclose(out[i, : len(s)], s[::-1], rtol=1e-6)


class TestSequencePadUnpad:
    def test_pad(self):
        seqs = ragged([2, 4], feat=(3,))
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", [3], dtype="float32", lod_level=1)
            pv = layers.fill_constant([1], "float32", -1.0)
            out, length = layers.sequence_pad(x, pv)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        o, l = exe.run(main, feed={"x": LoDTensor(seqs)}, fetch_list=[out, length])
        assert list(l) == [2, 4]
        np.testing.assert_allclose(o[0, :2], seqs[0], rtol=1e-6)
        assert np.all(o[0, 2:] == -1.0)

    def test_unpad_roundtrip(self):
        seqs = ragged([2, 4], feat=(3,))
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            dense = layers.data("dense", [8, 3], dtype="float32", append_batch_size=True)
            lens = layers.data("lens", [1], dtype="int32", append_batch_size=True)
            rag = layers.sequence_unpad(dense, lens)
            pooled = layers.sequence_pool(rag, "sum")
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        padded = np.zeros((2, 8, 3), dtype="float32")
        padded[0, :2], padded[1, :4] = seqs[0], seqs[1]
        # garbage beyond lengths must not leak into the pooled sum
        padded[0, 5:] = 99.0
        (o,) = exe.run(main, feed={"dense": padded, "lens": np.array([[2], [4]], dtype="int32")},
                       fetch_list=[pooled])
        np.testing.assert_allclose(o[0], seqs[0].sum(0), rtol=1e-5)
        np.testing.assert_allclose(o[1], seqs[1].sum(0), rtol=1e-5)


class TestSequenceConv:
    def test_golden_window(self):
        seqs = ragged([4, 6], feat=(5,), seed=3)
        (out,) = run_seq(
            lambda x: layers.sequence_conv(x, num_filters=7, filter_size=3, bias_attr=False),
            seqs,
        )
        # recover the filter from the program-built parameter: rerun with
        # identity check instead; simpler golden: compare vs numpy using the
        # actual initialized weight fetched from the scope
        scope = fluid.global_scope()
        wname = [n for n in scope.var_names() if ".w" in n][0]
        w = np.asarray(scope.find_var(wname))  # [3*5, 7]
        for i, s in enumerate(seqs):
            T = len(s)
            ctx = np.zeros((T, 3 * 5), dtype="float32")
            for t in range(T):
                parts = []
                for k in (-1, 0, 1):
                    parts.append(s[t + k] if 0 <= t + k < T else np.zeros(5, "f4"))
                ctx[t] = np.concatenate(parts)
            exp = ctx @ w
            np.testing.assert_allclose(out[i, :T], exp, rtol=1e-4, atol=1e-4)
            assert np.all(out[i, T:] == 0)


class TestSequenceEraseEnumerateConcat:
    def test_erase(self):
        seqs = [np.array([[2], [1], [2], [3]], dtype="int32"),
                np.array([[2], [2]], dtype="int32")]
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", [1], dtype="int32", lod_level=1)
            out = layers.sequence_erase(x, [2])
            lod = out._lod_ref
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        o, l = exe.run(main, feed={"x": LoDTensor(seqs)}, fetch_list=[out, lod])
        assert list(l) == [2, 0]
        assert o[0, :2, 0].tolist() == [1, 3]

    def test_enumerate(self):
        seqs = [np.array([[1], [2], [3]], dtype="int32")]
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", [1], dtype="int32", lod_level=1)
            out = layers.sequence_enumerate(x, win_size=2, pad_value=0)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        (o,) = exe.run(main, feed={"x": LoDTensor(seqs)}, fetch_list=[out])
        assert o[0, :3].tolist() == [[1, 2], [2, 3], [3, 0]]

    def test_concat(self):
        a = [np.ones((2, 3), "f4"), np.ones((1, 3), "f4") * 2]
        b = [np.ones((1, 3), "f4") * 5, np.ones((3, 3), "f4") * 6]
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            xa = layers.data("xa", [3], dtype="float32", lod_level=1)
            xb = layers.data("xb", [3], dtype="float32", lod_level=1)
            out = layers.sequence_concat([xa, xb])
            lod = out._lod_ref
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        o, l = exe.run(main, feed={"xa": LoDTensor(a), "xb": LoDTensor(b)},
                       fetch_list=[out, lod])
        assert list(l) == [3, 4]
        np.testing.assert_allclose(o[0, :3], np.concatenate([a[0], b[0]]), rtol=1e-6)
        np.testing.assert_allclose(o[1, :4], np.concatenate([a[1], b[1]]), rtol=1e-6)


class TestSequenceMask:
    def test_mask(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            lens = layers.data("lens", [], dtype="int32", append_batch_size=True)
            m = layers.sequence_mask(lens, maxlen=5, dtype="float32")
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        (o,) = exe.run(main, feed={"lens": np.array([2, 5, 0], "int32")}, fetch_list=[m])
        assert o.tolist() == [[1, 1, 0, 0, 0], [1, 1, 1, 1, 1], [0, 0, 0, 0, 0]]


class TestDynamicRNN:
    def test_simple_rnn_vs_numpy(self):
        h = 4
        seqs = ragged([3, 5, 2], feat=(6,), seed=7)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", [6], dtype="float32", lod_level=1)
            drnn = layers.DynamicRNN()
            with drnn.block():
                word = drnn.step_input(x)
                prev = drnn.memory(shape=[h], value=0.0)
                hid = layers.fc([word, prev], h, act="tanh", bias_attr=False)
                drnn.update_memory(prev, hid)
                drnn.output(hid)
            out = drnn()
            final = layers.sequence_last_step(out)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        o, f = exe.run(main, feed={"x": LoDTensor(seqs)}, fetch_list=[out, final])

        scope = fluid.global_scope()
        # recover the two fc weights (word, prev order) from the sub-block muls
        sub = main.blocks[
            [o for o in main.global_block().ops if o.type == "dynamic_rnn"][0].attrs["sub_block"]
        ]
        wnames = [o.inputs["Y"][0] for o in sub.ops if o.type == "mul"]
        w1 = np.asarray(scope.find_var(wnames[0]))
        w2 = np.asarray(scope.find_var(wnames[1]))
        for i, s in enumerate(seqs):
            hprev = np.zeros(h, "f4")
            for t in range(len(s)):
                hprev = np.tanh(s[t] @ w1 + hprev @ w2)
                np.testing.assert_allclose(o[i, t], hprev, rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(f[i], hprev, rtol=1e-4, atol=1e-5)
            assert np.all(o[i, len(s):] == 0)

    def test_trainable(self):
        """Gradients flow through the scan: loss decreases."""
        seqs = ragged([3, 5, 2, 4], feat=(6,), seed=1)
        tgt = np.array([[0.5], [-0.3], [0.1], [0.9]], dtype="float32")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", [6], dtype="float32", lod_level=1)
            y = layers.data("y", [1], dtype="float32")
            drnn = layers.DynamicRNN()
            with drnn.block():
                word = drnn.step_input(x)
                prev = drnn.memory(shape=[8], value=0.0)
                hid = layers.fc([word, prev], 8, act="tanh")
                drnn.update_memory(prev, hid)
                drnn.output(hid)
            last = layers.sequence_last_step(drnn())
            pred = layers.fc(last, 1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        feed = {"x": LoDTensor(seqs), "y": tgt}
        losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0][0]) for _ in range(30)]
        assert losses[-1] < losses[0] * 0.5


class TestBucketing:
    def test_bucket_policy(self):
        assert bucket_length(1) == 8
        assert bucket_length(8) == 8
        assert bucket_length(9) == 16
        assert bucket_length(64) == 64
        assert bucket_length(65) == 128
        assert bucket_length(1000) == 1024

    def test_bounded_recompiles(self):
        """Feeds whose max_len drifts within one bucket reuse the executable."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", [3], dtype="float32", lod_level=1)
            out = layers.sequence_pool(x, "sum")
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        exe.run(main, feed={"x": LoDTensor(ragged([2, 3]))}, fetch_list=[out])
        n_compiled = len(exe._cache)
        for lens in ([4, 5], [5, 8]):  # all bucket to T=8
            exe.run(main, feed={"x": LoDTensor(ragged(lens))}, fetch_list=[out])
        assert len(exe._cache) == n_compiled
