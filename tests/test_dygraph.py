"""Dygraph tests (reference: test_imperative_*.py — including the
dygraph == static-graph loss parity pattern, SURVEY.md §4.6)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import dygraph


class MLP(dygraph.Layer):
    def __init__(self):
        super().__init__("mlp")
        self.fc1 = dygraph.Linear(16, 32, act="relu")
        self.fc2 = dygraph.Linear(32, 4)

    def forward(self, x):
        return self.fc2(self.fc1(x))


def test_eager_forward_backward():
    with dygraph.guard():
        model = MLP()
        x = dygraph.to_variable(np.random.rand(8, 16).astype("f4"))
        label = dygraph.to_variable(np.random.randint(0, 4, (8, 1)))
        logits = model(x)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label)
        )
        loss.backward()
        grads = [p.gradient() for p in model.parameters()]
        assert all(g is not None for g in grads)
        assert any(np.abs(g).sum() > 0 for g in grads)


def test_eager_training_converges():
    rng = np.random.RandomState(0)
    protos = rng.randn(4, 16).astype("f4") * 2
    with dygraph.guard():
        model = MLP()
        opt = fluid.optimizer.Adam(learning_rate=0.01)
        losses = []
        for step in range(60):
            lab = rng.randint(0, 4, (32, 1))
            xv = protos[lab[:, 0]] + 0.5 * rng.randn(32, 16).astype("f4")
            x = dygraph.to_variable(xv)
            label = dygraph.to_variable(lab)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(model(x), label)
            )
            loss.backward()
            opt.minimize(loss, parameter_list=model.parameters())
            model.clear_gradients()
            losses.append(float(loss.numpy()[0]))
        assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])


def test_dygraph_matches_static_graph():
    """Same init + same data => dygraph loss == static-graph loss
    (the reference's test_imperative_mnist pattern)."""
    rng = np.random.RandomState(3)
    w1 = rng.randn(8, 8).astype("f4") * 0.3
    b1 = np.zeros(8, "f4")
    w2 = rng.randn(8, 1).astype("f4") * 0.3
    xv = rng.rand(4, 8).astype("f4")
    yv = xv.sum(1, keepdims=True).astype("f4")

    # static
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(
            x, 8, act="relu",
            param_attr=fluid.ParamAttr(initializer=fluid.initializer.NumpyArrayInitializer(w1)),
        )
        pred = fluid.layers.fc(
            h, 1, bias_attr=False,
            param_attr=fluid.ParamAttr(initializer=fluid.initializer.NumpyArrayInitializer(w2)),
        )
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (static_loss,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)

    # dygraph with identical weights
    with dygraph.guard():
        lin1 = dygraph.Linear(8, 8, act="relu")
        lin1.weight.set_value(w1)
        lin1.bias.set_value(b1)
        lin2 = dygraph.Linear(8, 1)
        lin2.weight.set_value(w2)
        lin2.bias.set_value(np.zeros(1, "f4"))
        out = lin2(lin1(dygraph.to_variable(xv)))
        dloss = fluid.layers.mean(
            fluid.layers.square_error_cost(out, dygraph.to_variable(yv))
        )
        np.testing.assert_allclose(dloss.numpy(), static_loss, rtol=1e-5)


def test_dygraph_conv_bn_and_state_dict(tmp_path):
    with dygraph.guard():
        conv = dygraph.Conv2D(1, 4, 3)
        bn = dygraph.BatchNorm(4)
        x = dygraph.to_variable(np.random.rand(2, 1, 8, 8).astype("f4"))
        y = bn(conv(x))
        s = fluid.layers.mean(y)
        s.backward()
        assert conv.weight.gradient() is not None

        class Net(dygraph.Layer):
            def __init__(self, c, b):
                super().__init__("net")
                self.c = c
                self.b = b

        net = Net(conv, bn)
        state = net.state_dict()
        # conv w/b + bn scale/bias + bn running mean/variance
        assert len(state) == 6
        d = str(tmp_path / "dyckpt")
        dygraph.save_persistables(net, d)
        loaded = dygraph.load_persistables(d)
        for k, v in net.state_dict().items():
            np.testing.assert_allclose(loaded[k], v)


def test_embedding_and_dropout_layers():
    with dygraph.guard():
        emb = dygraph.Embedding([50, 8])
        ids = dygraph.to_variable(np.array([[1], [2], [3]]))
        e = emb(ids)
        assert e.shape == (3, 8)
        drop = dygraph.Dropout(0.5)
        y = drop(e)
        loss = fluid.layers.mean(y)
        loss.backward()
        assert emb.weight.gradient() is not None
        drop.eval()
        y2 = drop(e.detach())
        np.testing.assert_allclose(y2.numpy(), e.numpy())


def test_dygraph_data_parallel_mesh_parity():
    """weak-item regression: DataParallel + a real mesh — batch sharded over
    dp, eager ops auto-partition (GSPMD), losses match the unsharded run."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.parallel import make_mesh

    rng = np.random.RandomState(0)
    xv = rng.rand(16, 8).astype("f4")
    yv = xv.sum(1, keepdims=True).astype("f4")

    def run(mesh):
        with dygraph.guard():
            layer = dygraph.Linear(8, 1)
            params = layer.parameters()
            params[0].value = jnp.full((8, 1), 0.1, jnp.float32)
            params[1].value = jnp.zeros((1,), jnp.float32)
            model = dygraph.parallel.DataParallel(layer, mesh=mesh)
            opt = fluid.optimizer.SGD(0.1)
            losses = []
            for _ in range(4):
                x, y = jnp.asarray(xv), jnp.asarray(yv)
                if mesh is not None:
                    x = jax.device_put(x, NamedSharding(mesh, P("dp")))
                    y = jax.device_put(y, NamedSharding(mesh, P("dp")))
                pred = model(dygraph.to_variable(x))
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, dygraph.to_variable(y)))
                loss.backward()
                model.apply_collective_grads()
                opt.minimize(loss, parameter_list=model.parameters())
                layer.clear_gradients()
                losses.append(float(loss.numpy().reshape(-1)[0]))
            return losses

    base = run(None)
    sharded = run(make_mesh((8,), ("dp",)))
    np.testing.assert_allclose(sharded, base, rtol=1e-5, atol=1e-6)


def test_dygraph_eager_optimizers_converge():
    """every major optimizer family has an eager update rule now."""
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    xv = rng.rand(32, 6).astype("f4")
    w_true = rng.rand(6, 1).astype("f4")
    yv = xv @ w_true

    for make in (lambda: fluid.optimizer.Adagrad(0.3),
                 lambda: fluid.optimizer.RMSProp(0.05),
                 lambda: fluid.optimizer.Adamax(0.05),
                 lambda: fluid.optimizer.Adadelta(1.0)):
        with dygraph.guard():
            layer = dygraph.Linear(6, 1)
            opt = make()
            losses = []
            for _ in range(60):
                pred = layer(dygraph.to_variable(xv))
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, dygraph.to_variable(yv)))
                loss.backward()
                opt.minimize(loss, parameter_list=layer.parameters())
                layer.clear_gradients()
                losses.append(float(loss.numpy().reshape(-1)[0]))
            assert losses[-1] < losses[0] * 0.5, (type(opt).__name__, losses[0], losses[-1])


def test_dygraph_new_layers_forward_backward():
    """Conv2DTranspose / PRelu / GRUUnit eager layers run and backprop."""
    rng = np.random.RandomState(5)
    with dygraph.guard():
        ct = dygraph.Conv2DTranspose(3, 5, 3, stride=2, padding=1)
        x = dygraph.to_variable(rng.rand(2, 3, 4, 4).astype("f4"))
        y = ct(x)
        assert y.numpy().shape == (2, 5, 7, 7)

        pr = dygraph.PRelu(mode="channel", channel=5)
        z = pr(y)
        loss = fluid.layers.mean(z)
        loss.backward()
        assert np.isfinite(ct.parameters()[0].gradient()).all()

    with dygraph.guard():
        gru = dygraph.GRUUnit(3 * 8)
        x = dygraph.to_variable(rng.rand(4, 24).astype("f4"))
        h0 = dygraph.to_variable(rng.rand(4, 8).astype("f4"))
        h, _, _ = gru(x, h0)
        assert h.numpy().shape == (4, 8)
        loss = fluid.layers.mean(h)
        loss.backward()
        assert np.abs(gru.parameters()[0].gradient()).sum() > 0
