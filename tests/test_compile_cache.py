"""FLAGS_compile_cache_dir: XLA's persistent compilation cache pays the
cold-start `executor.compile` cost once per machine, not once per
process.  Verified the only honest way — two fresh subprocesses."""
import json
import os
import subprocess
import sys

CHILD = r"""
import json
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import monitor

main_p, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main_p, startup):
    x = fluid.layers.data("x", [256], dtype="float32")
    y = fluid.layers.data("y", [1], dtype="float32")
    h = x
    for _ in range(6):
        h = fluid.layers.fc(h, 256, act="relu")
    loss = fluid.layers.mean(
        fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
    fluid.optimizer.Adam(1e-3).minimize(loss)
exe = fluid.Executor(fluid.CPUPlace())
scope = fluid.Scope()
exe.run(startup, scope=scope)
monitor.enable()
feed = {"x": np.zeros((32, 256), "f4"), "y": np.zeros((32, 1), "f4")}
exe.run(main_p, feed=feed, fetch_list=[loss], scope=scope)
spans = monitor.json_snapshot()["spans"]
print(json.dumps({"compile_s": spans["executor.compile"]["total_s"]}))
"""


def _run_child(cache_dir):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["FLAGS_compile_cache_dir"] = cache_dir
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    out = subprocess.run([sys.executable, "-c", CHILD], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, f"child failed:\n{out.stderr[-3000:]}"
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_compile_cache_hits_across_processes(tmp_path):
    cache = str(tmp_path / "xla_cache")
    first = _run_child(cache)["compile_s"]
    assert os.listdir(cache), "first process wrote no cache entries"
    second = _run_child(cache)["compile_s"]
    # Measured locally: 0.82s cold vs 0.055s cache hit (~15x).  Gate at 3x
    # so shared-CI timer noise can't flake the test while a broken cache
    # (second == first) still fails loudly.
    assert second < first / 3, (
        f"persistent compile cache miss: cold {first:.3f}s vs second "
        f"process {second:.3f}s (expected an order-of-magnitude drop)")


def test_compile_cache_flag_registered():
    import paddle_tpu as fluid

    assert fluid.get_flags("FLAGS_compile_cache_dir") == {
        "FLAGS_compile_cache_dir": ""}
