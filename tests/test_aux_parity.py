"""Aux parity batch: flags registry + check_nan_inf, auc/mean_iou metric
ops, LarsMomentum/EMA/ModelAverage, Predictor, Dataset/train_from_dataset."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import recordio


def test_flags_registry_and_env():
    assert fluid.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"] is False
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    assert fluid.flags.flag("FLAGS_check_nan_inf") is True
    fluid.set_flags({"FLAGS_check_nan_inf": False})
    with pytest.raises(KeyError):
        fluid.set_flags({"FLAGS_no_such_flag": 1})


def test_check_nan_inf_guard():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [3], dtype="float32")
        y = fluid.layers.log(x)  # log of a negative -> nan
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    bad = np.array([[1.0, -1.0, 2.0]], "float32")
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(RuntimeError, match="NaN"):
            exe.run(main, feed={"x": bad}, fetch_list=[y], scope=scope)
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})
    exe.run(main, feed={"x": bad}, fetch_list=[y], scope=scope)  # off: no raise


def test_auc_layer_streaming():
    from sklearn_free_auc import ref_auc  # noqa: F401 - defined below
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pred = fluid.layers.data("pred", [2], dtype="float32")
        label = fluid.layers.data("label", [1], dtype="int64")
        auc_out = fluid.layers.auc(pred, label, num_thresholds=1023)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    all_p, all_l = [], []
    v = None
    for _ in range(3):  # streaming accumulation across batches
        lab = rng.randint(0, 2, (64, 1)).astype("int64")
        p1 = np.clip(0.35 * lab[:, 0] + 0.4 * rng.rand(64), 0, 1).astype("float32")
        pred_v = np.stack([1 - p1, p1], axis=1)
        (v,) = exe.run(main, feed={"pred": pred_v, "label": lab},
                       fetch_list=[auc_out], scope=scope)
        all_p.append(p1)
        all_l.append(lab[:, 0])
    got = float(np.asarray(v).reshape(-1)[0])
    expected = ref_auc(np.concatenate(all_l), np.concatenate(all_p))
    assert abs(got - expected) < 0.02, (got, expected)


def test_mean_iou_layer():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        p = fluid.layers.data("p", [6], dtype="int64")
        l = fluid.layers.data("l", [6], dtype="int64")
        iou, wrong, correct = fluid.layers.mean_iou(p, l, num_classes=3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    pv = np.array([[0, 0, 1, 1, 2, 2]], "int64")
    lv = np.array([[0, 1, 1, 1, 2, 0]], "int64")
    (iv, wv, cv) = exe.run(main, feed={"p": pv, "l": lv},
                           fetch_list=[iou, wrong, correct], scope=scope)
    # class0: inter 1, union |pred0|+|lab0|-1 = 2+2-1=3 -> 1/3
    # class1: inter 2, union 2+3-2=3 -> 2/3 ; class2: inter 1, union 2+1-1=2 -> 1/2
    np.testing.assert_allclose(float(np.asarray(iv)[0]), (1/3 + 2/3 + 1/2) / 3, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(cv), [1, 2, 1])


def test_lars_momentum_step_golden():
    """Single-step golden for the lars_momentum op (LARS is a large-batch
    method — convergence on a toy fc is not meaningful, the update rule is)."""
    from op_test import OpTest

    rng = np.random.RandomState(1)
    p = rng.rand(6).astype("f4")
    g = rng.rand(6).astype("f4")
    v = rng.rand(6).astype("f4")
    lr = np.array([0.5], "f4")
    mu, coeff, wd = 0.9, 0.001, 0.0005
    pn = np.sqrt((p ** 2).sum())
    gn = np.sqrt((g ** 2).sum())
    local_lr = 0.5 * coeff * pn / (gn + wd * pn)
    v_new = mu * v + local_lr * (g + wd * p)
    p_new = p - v_new

    class T(OpTest):
        def setUp(self):
            self.op_type = "lars_momentum"
            self.inputs = {"Param": p, "Grad": g, "Velocity": v, "LearningRate": lr}
            self.outputs = {"ParamOut": p_new, "VelocityOut": v_new}
            self.attrs = {"mu": mu, "lars_coeff": coeff, "lars_weight_decay": wd}

    T().check_output(atol=1e-6)

    # API surface: minimize() emits the op
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.LarsMomentum(20.0, 0.9).minimize(loss)
    assert "lars_momentum" in [op.type for op in main.global_block().ops]


def test_ema_apply_restore():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 2
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        pred = fluid.layers.fc(x, 1, param_attr=fluid.ParamAttr(name="ema_w"))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
        ema = fluid.optimizer.ExponentialMovingAverage(0.9)
        ema.update()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    for _ in range(10):
        xv = rng.rand(8, 4).astype("f4")
        exe.run(main, feed={"x": xv, "y": xv.sum(1, keepdims=True)},
                fetch_list=[loss], scope=scope)
    live = np.asarray(scope.find_var("ema_w")).copy()
    with ema.apply(exe, scope):
        inside = np.asarray(scope.find_var("ema_w")).copy()
        assert not np.allclose(inside, live)  # shadow differs from live
    after = np.asarray(scope.find_var("ema_w"))
    np.testing.assert_array_equal(after, live)  # restored


def test_model_average_apply_restore():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        pred = fluid.layers.fc(x, 1, param_attr=fluid.ParamAttr(name="avg_w"))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.2).minimize(loss)
        ma = fluid.optimizer.ModelAverage()
        ma.update()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    snaps = []
    for _ in range(5):
        xv = rng.rand(8, 4).astype("f4")
        exe.run(main, feed={"x": xv, "y": xv.sum(1, keepdims=True)},
                fetch_list=[loss], scope=scope)
        snaps.append(np.asarray(scope.find_var("avg_w")).copy())
    with ma.apply(exe, scope):
        avg = np.asarray(scope.find_var("avg_w"))
        np.testing.assert_allclose(avg, np.mean(snaps, axis=0), atol=1e-5)
    np.testing.assert_array_equal(np.asarray(scope.find_var("avg_w")), snaps[-1])


def test_predictor_roundtrip(tmp_path):
    from paddle_tpu.inference import PredictConfig, create_predictor

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 4
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [6], dtype="float32")
        h = fluid.layers.fc(x, 8, act="relu")
        out = fluid.layers.fc(h, 3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    xv = np.random.RandomState(0).rand(4, 6).astype("f4")
    (ref,) = exe.run(main, feed={"x": xv}, fetch_list=[out], scope=scope)

    d = str(tmp_path / "model")
    fluid.io.save_inference_model(d, ["x"], [out], exe, main_program=main, scope=scope)
    pred = create_predictor(PredictConfig(d, fluid.CPUPlace()))
    (got,) = pred.run({"x": xv})
    np.testing.assert_allclose(got, ref, atol=1e-6)
    clone = pred.clone()
    (got2,) = clone.run({"x": xv})
    np.testing.assert_allclose(got2, ref, atol=1e-6)
    with pytest.raises(KeyError):
        pred.run({})


def test_dataset_train_from_dataset(tmp_path):
    # write two recordio shards with (feature, label) samples
    rng = np.random.RandomState(0)
    w_true = rng.rand(5, 1).astype("f4")
    files = []
    for shard in range(2):
        p = str(tmp_path / f"part-{shard}.rio")
        samples = []
        for _ in range(40):
            f = rng.rand(5).astype("f4")
            samples.append((f, (f @ w_true).astype("f4")))
        recordio.write_arrays(p, samples)
        files.append(p)

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [5], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)

    ds = fluid.InMemoryDataset()
    ds.set_batch_size(8)
    ds.set_thread(2)
    ds.set_filelist(files)
    ds.set_use_var([x, y])
    ds.load_into_memory()
    ds.local_shuffle(seed=0)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    logs = exe.train_from_dataset(main, ds, scope=scope, fetch_list=[loss],
                                  print_period=1)
    first = float(list(logs[0][1].values())[0][0])
    last = float(list(logs[-1][1].values())[0][0])
    assert last < first, (first, last)

    # queue mode streams the same sample count
    qd = fluid.QueueDataset()
    qd.set_batch_size(8)
    qd.set_filelist(files)
    qd.set_use_var([x, y])
    n = sum(1 for _ in qd.batches())
    assert n == 10  # 80 samples / 8


# tiny dependency-free reference AUC
import sys


def _ref_auc(labels, scores):
    order = np.argsort(-scores)
    labels = labels[order]
    tp = np.cumsum(labels)
    fp = np.cumsum(1 - labels)
    tp = np.concatenate([[0], tp])
    fp = np.concatenate([[0], fp])
    if tp[-1] == 0 or fp[-1] == 0:
        return 0.0
    return float(np.trapz(tp, fp) / (tp[-1] * fp[-1]))


class _M:
    ref_auc = staticmethod(_ref_auc)


sys.modules["sklearn_free_auc"] = _M()


def test_debugger_graphviz_dump(tmp_path):
    from paddle_tpu import debugger

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(x, 1), y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    dots = debugger.draw_program(main, str(tmp_path / "prog"))
    dot = dots[0]
    assert "digraph" in dot and "backward" in dot and "sgd" in dot
    assert (tmp_path / "prog.block0.dot").exists()
    # persistable params render with the param fill color
    assert "#ffe4b5" in dot


def test_dpsgd_trains_with_noise():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 12
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [6], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Dpsgd(0.05, clip=5.0, sigma=0.01).minimize(loss)
    assert "dpsgd" in [op.type for op in main.global_block().ops]
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    w = rng.rand(6, 1).astype("f4")
    losses = []
    for _ in range(60):
        xv = rng.rand(16, 6).astype("f4")
        (lv,) = exe.run(main, feed={"x": xv, "y": xv @ w}, fetch_list=[loss],
                        scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5


def test_scope_guard_and_name_scope():
    s = fluid.Scope()
    with fluid.scope_guard(s):
        assert fluid.global_scope() is s
    assert fluid.global_scope() is not s
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("ns_x", [4], dtype="float32")
        with fluid.name_scope("encoder"):
            h1 = fluid.layers.fc(x, 4)
        with fluid.name_scope("encoder"):  # sibling scope must dedup
            h2 = fluid.layers.fc(x, 4)
        with fluid.name_scope("outer"):
            with fluid.name_scope("inner"):  # nesting composes
                h3 = fluid.layers.fc(x, 4)
    assert h1.name.startswith("encoder/")
    assert h2.name.startswith("encoder_1/")
    assert h1.name.split("/")[-1] != "" and h1.name != h2.name
    assert h3.name.startswith("outer/inner/")


def test_py_func_host_callable():
    def host_squared_plus(a, b):
        # returns a python-made float64 array: the lowering must cast to the
        # declared float32 instead of crashing inside pure_callback
        return (a * a + b).astype("float64")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [3], dtype="float32")
        y = fluid.layers.data("y", [3], dtype="float32")
        out = main.global_block().create_var("pyout", shape=(2, 3), dtype="float32")
        fluid.layers.py_func(host_squared_plus, [x, y], out)
        final = fluid.layers.scale(out, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    xv = np.arange(6, dtype="f4").reshape(2, 3)
    yv = np.ones((2, 3), "f4")
    (got,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[final], scope=scope)
    np.testing.assert_allclose(got, (xv * xv + 1) * 2, atol=1e-6)


def test_backward_module_and_evaluator_shims():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("bx", [3], dtype="float32")
        y = fluid.layers.scale(x, scale=4.0)
        grads = fluid.gradients(y, [x])  # backward.gradients alias
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (g,) = exe.run(main, feed={"bx": np.ones((2, 3), "f4")},
                   fetch_list=[grads[0]], scope=scope)
    np.testing.assert_allclose(g, 4.0)
    m = fluid.evaluator.Accuracy()
    assert m is not None
