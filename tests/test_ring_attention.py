"""Ring attention: sequence-parallel result must match single-device
reference attention exactly (up to fp tolerance)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def _ref_attention(q, k, v, causal):
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(q.shape[-1])
    if causal:
        L = q.shape[2]
        mask = np.triu(np.ones((L, L), bool), 1)
        s = np.where(mask, -1e9, s)
    s = s - s.max(-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    import jax
    from paddle_tpu.parallel.ring_attention import ring_attention
    from paddle_tpu.parallel import make_mesh

    rng = np.random.RandomState(0)
    B, H, L, D = 2, 4, 32, 16
    q = rng.randn(B, H, L, D).astype("f4")
    k = rng.randn(B, H, L, D).astype("f4")
    v = rng.randn(B, H, L, D).astype("f4")
    ref = _ref_attention(q, k, v, causal)

    # single device path
    out1 = np.asarray(ring_attention(q, k, v, causal=causal))
    np.testing.assert_allclose(out1, ref, atol=2e-5, rtol=2e-5)

    # 8-way sequence parallel
    mesh = make_mesh((8,), ("sp",))
    out8 = np.asarray(ring_attention(q, k, v, mesh=mesh, causal=causal, batch_axis=None))
    np.testing.assert_allclose(out8, ref, atol=2e-5, rtol=2e-5)

    # dp x sp combined
    mesh2 = make_mesh((2, 4), ("dp", "sp"))
    out24 = np.asarray(ring_attention(q, k, v, mesh=mesh2, causal=causal))
    np.testing.assert_allclose(out24, ref, atol=2e-5, rtol=2e-5)


def test_ring_attention_layer_in_program():
    from paddle_tpu.parallel import make_mesh

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        q = fluid.layers.data("q", [4, 16, 8], dtype="float32")
        k = fluid.layers.data("k", [4, 16, 8], dtype="float32")
        v = fluid.layers.data("v", [4, 16, 8], dtype="float32")
        out = fluid.layers.ring_attention(q, k, v, causal=True)
    rng = np.random.RandomState(1)
    qv = rng.randn(2, 4, 16, 8).astype("f4")
    kv = rng.randn(2, 4, 16, 8).astype("f4")
    vv = rng.randn(2, 4, 16, 8).astype("f4")
    ref = _ref_attention(qv, kv, vv, True)

    exe = fluid.Executor(fluid.CPUPlace())
    (r1,) = exe.run(main, feed={"q": qv, "k": kv, "v": vv}, fetch_list=[out])
    np.testing.assert_allclose(r1, ref, atol=2e-5, rtol=2e-5)

    mesh = make_mesh((2, 4), ("dp", "sp"))
    compiled = fluid.CompiledProgram(main).with_mesh(mesh)
    (r2,) = exe.run(compiled, feed={"q": qv, "k": kv, "v": vv}, fetch_list=[out])
    np.testing.assert_allclose(r2, ref, atol=2e-5, rtol=2e-5)
