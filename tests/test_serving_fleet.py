"""Serving-fleet chaos suite (ISSUE 18 acceptance): real multi-process
replica fleets under kill / store-fault / drain chaos.

The properties under test:

  1. a replica SIGKILLed under load loses ONLY its own in-flight
     requests (classified `reason="replica_down"`), new traffic
     redistributes to the survivor, the supervisor restarts the corpse,
     and the router's ledger reconciles exactly
     (requests == completed + classified errors);
  2. a store fault mid-rolling-publish — rotted content (NaN weights)
     or persistent EIO — HALTS the roll on the failing rung, the fleet
     converges back on the last good version on every replica (zero
     requests ever served by the bad version), and the halt/convergence
     is visible in `serve_trace --fleet --check` and gated by
     `perf_report --check --check-roll-convergence`;
  3. one replica's rejection persists a quarantine marker next to the
     snapshot, so the next roll over the same source fast-rejects
     fleet-wide without re-running the ladder;
  4. SIGTERM drains: the beat flips to draining, the router stops
     dispatching to that replica, in-flight requests serve out, and
     NOTHING is shed by the shutdown (exit 0 = retired, not restarted);
  5. a roll interrupted supervisor-side resumes from the persisted
     ROLL.json state (`resume_roll`).

In-process units ride along: ReplicaBeat/FleetHealth status machine,
router dispatch policy (inflight caps, suspicion, classified
no-replica refusals), registry staging API, and the fleet gates of
perf_report / serve_trace over crafted streams.
"""
import json
import os
import signal
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu import monitor
from paddle_tpu.errors import ServingError
from paddle_tpu.serving import ServingFleet

from test_serving import D_IN, _expected, _save_model

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tools"))
import perf_report  # noqa: E402
import serve_trace  # noqa: E402

FLEET_KW = dict(buckets=(2, 4), hb_interval_s=0.15, miss_factor=4.0)


@pytest.fixture
def mon():
    monitor.reset()
    monitor.enable()
    yield monitor
    monitor.disable()
    monitor.reset()


def _router_events(fleet, action=None):
    path = os.path.join(fleet.root, "telemetry", "router.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    evs = [r for r in recs if r.get("kind") == "fleet_event"]
    return [e for e in evs if e.get("action") == action] if action else evs


def _wait_event(fleet, action, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        hits = _router_events(fleet, action)
        if hits:
            return hits
        time.sleep(0.1)
    raise AssertionError(
        f"no {action!r} fleet_event within {timeout}s; have "
        f"{[e['action'] for e in _router_events(fleet)]}")


# --------------------------------------------------------------------------
# in-process units
# --------------------------------------------------------------------------

def test_replica_beat_and_fleet_health_status_machine(tmp_path):
    from paddle_tpu.dist_resilience import FleetHealth, ReplicaBeat

    hb = str(tmp_path / "hb")
    os.makedirs(hb)
    payload = {"q": 1, "draining": False, "port": 7}
    beat = ReplicaBeat(hb, 0, 2, interval_s=0.05,
                       payload_fn=lambda: dict(payload)).start()
    health = FleetHealth(hb, 2, interval_s=0.05, miss_factor=4.0,
                         startup_grace_s=0.4)
    try:
        time.sleep(0.15)
        table = health.poll()
        assert table[0]["status"] == "alive"
        assert table[0]["tel"]["port"] == 7
        assert table[1]["status"] == "booting"  # absent, within grace
        assert health.dispatchable() == [0]
        payload["draining"] = True
        beat.beat_now()
        time.sleep(0.1)
        assert health.poll()[0]["status"] == "draining"
        assert health.dispatchable() == []
        assert health.alive() == [0]  # process still live while draining
    finally:
        beat.stop(mark_down=True)
    # tombstone is immediate death; grace expiry kills the never-seen
    time.sleep(0.45)
    table = health.poll()
    assert table[0]["status"] == "dead" and table[1]["status"] == "dead"
    # restart clears the corpse's files: fresh grace, fresh seq space
    health.note_restart(0)
    assert health.poll()[0]["status"] == "booting"
    beat2 = ReplicaBeat(hb, 0, 2, interval_s=0.05,
                        payload_fn=lambda: dict(payload)).start()
    try:
        time.sleep(0.15)
        assert health.poll()[0]["status"] == "draining"
    finally:
        beat2.stop()


class _FakeHealth:
    def __init__(self, table):
        self.table = table
        self.world = len(table)

    def poll(self):
        return {r: dict(info) for r, info in self.table.items()}


def test_router_dispatch_policy_classified(mon):
    from paddle_tpu.serving.router import Router

    alive = {"status": "alive", "seq": 5, "age_s": 0.0,
             "tel": {"port": 1, "q": 0, "p99": 1.0}}
    # no live replica at all -> replica_down
    r = Router(_FakeHealth({0: {**alive, "status": "dead", "tel": None}}))
    with pytest.raises(ServingError) as ei:
        r.infer("m", {"x": np.ones((1, D_IN), "f4")})
    assert ei.value.reason == "replica_down"
    # draining replicas take no new traffic either
    r = Router(_FakeHealth({0: {**alive, "status": "draining"}}))
    with pytest.raises(ServingError) as ei:
        r.infer("m", {"x": np.ones((1, D_IN), "f4")})
    assert ei.value.reason == "replica_down"
    # every candidate at its inflight cap -> overload (backpressure)
    r = Router(_FakeHealth({0: dict(alive)}), inflight_cap=1)
    with r._lock:
        r._inflight[0] = 1
    with pytest.raises(ServingError) as ei:
        r.infer("m", {"x": np.ones((1, D_IN), "f4")})
    assert ei.value.reason == "overload"
    # a suspect is skipped until its beat seq advances past suspicion
    r = Router(_FakeHealth({0: dict(alive)}))
    r._mark_suspect(0, 5)
    with pytest.raises(ServingError) as ei:
        r.infer("m", {"x": np.ones((1, D_IN), "f4")})
    assert ei.value.reason == "replica_down"
    r.health.table[0]["seq"] = 6  # beat advanced: forgiven
    pick = r._pick(r.health.poll())
    assert pick["rank"] == 0
    # ledger counted every classified refusal
    s = r.stats()
    assert s["by_reason"]["replica_down"] >= 1
    assert s["requests"] == s["completed"] + s["errors"]


def test_router_least_loaded_pick():
    from paddle_tpu.serving.router import Router

    def info(port, q, p99):
        return {"status": "alive", "seq": 3, "age_s": 0.0,
                "tel": {"port": port, "q": q, "p99": p99}}

    r = Router(_FakeHealth({0: info(1, 5, 9.0), 1: info(2, 0, 1.0)}))
    assert r._pick(r.health.poll())["rank"] == 1  # shallower queue wins
    # router-side inflight outranks the (stale-able) beat telemetry
    with r._lock:
        r._inflight[1] = 3
    assert r._pick(r.health.poll())["rank"] == 0


def test_router_suspicion_clears_on_replica_restart(mon):
    """A crash-restarted replica must not stay benched: the fresh
    incarnation's beat seq restarts at 1, far BELOW the dead
    incarnation's suspicion seq, so `seq > at` alone would keep it
    suspect for the old incarnation's lifetime worth of beats — a total
    outage at n_replicas=1."""
    from paddle_tpu.serving.router import Router

    alive = {"status": "alive", "seq": 1, "age_s": 0.0,
             "tel": {"port": 1, "q": 0, "p99": 1.0}}
    # long-lived incarnation died at seq 50_000; fresh one beats seq=1
    r = Router(_FakeHealth({0: dict(alive)}))
    r._mark_suspect(0, 50_000)
    pick = r._pick(r.health.poll())
    assert pick["rank"] == 0  # seq below suspicion point => forgiven
    with r._lock:
        assert 0 not in r._suspect
    # the supervisor also clears suspicion explicitly on relaunch
    r = Router(_FakeHealth({0: dict(alive)}))
    r._mark_suspect(0, 50_000)
    r.note_restart(0)
    assert r._pick(r.health.poll())["rank"] == 0
    # unchanged: seq stuck AT the suspicion point stays suspect
    r = Router(_FakeHealth({0: {**alive, "seq": 7}}))
    r._mark_suspect(0, 7)
    with pytest.raises(ServingError) as ei:
        r._pick(r.health.poll())
    assert ei.value.reason == "replica_down"


def test_roll_reconciles_replica_that_died_after_acking(tmp_path, mon):
    """Split-brain window: a replica that dies AFTER acking its
    activate reboots from ACTIVE.json — still the last good version —
    and the activate loop skips acked ranks.  The pre-finalize
    reconcile pass must catch the revert and re-stage + re-activate."""
    fleet = ServingFleet({"m": "/old"}, n_replicas=2,
                         root=str(tmp_path / "fleet"), start=False)
    # rank 1 acked, then died and rebooted on last good (empty staged slot)
    active = {0: "/new", 1: "/old"}
    staged = {0: False, 1: False}
    ops = []

    def fake_rpc(rank, msg, recover_timeout=60.0):
        op = msg["op"]
        ops.append((rank, op))
        if op == "active_src":
            return {"ok": True, "src": active[rank], "version": 1}
        if op == "stage":
            staged[rank] = True
            return {"ok": True, "version": 2, "src": msg["src"]}
        if op == "activate":
            if not staged[rank]:
                return {"ok": False, "reason": "model_missing",
                        "error": "nothing staged"}
            active[rank] = "/new"
            staged[rank] = False
            return {"ok": True, "version": 2}
        raise AssertionError(f"unexpected op {op!r}")

    fleet._control_rpc = fake_rpc
    roll = {"model": "m", "src": "/new", "ctl": "roll-t",
            "phase": "activate", "verified": [0, 1], "acked": [0, 1],
            "last_good": "/old"}
    fleet._reconcile_acked(roll, recover_timeout=1.0)
    assert active == {0: "/new", 1: "/new"}
    # rank 1 went through the full ladder again, rank 0 was only probed
    assert (1, "stage") in ops and (1, "activate") in ops
    assert (0, "stage") not in ops
    assert _router_events(fleet, "replica_reactivated")


def test_sigterm_racing_boot_retires_instead_of_restarting(tmp_path, mon):
    """A SIGTERM that lands while the replica is still importing (before
    main() installs the drain handler) kills it with -SIGTERM.  The
    supervisor must treat that as deliberate retirement — restarting
    would undo an operator scale-down racing a slow boot."""
    v1 = _save_model(str(tmp_path / "m_v1"), 1.0)
    fleet = ServingFleet({"m": v1}, n_replicas=2,
                         root=str(tmp_path / "fleet"), **FLEET_KW)
    try:
        victim = fleet._replicas[1]["proc"]
        victim.send_signal(signal.SIGTERM)  # immediately: mid-import
        rc = victim.wait(timeout=120)
        assert rc in (0, -signal.SIGTERM), rc
        _wait_event(fleet, "replica_retired", timeout=30)
        assert fleet._replicas[1]["retired"]
        assert fleet._replicas[1]["proc"] is victim, "rank 1 was restarted"
        assert not _router_events(fleet, "replica_restarted")
        # the survivor still serves
        fleet.wait_healthy(min_replicas=1, timeout=120)
        xv = np.ones((2, D_IN), "f4")
        (out,) = fleet.infer("m", {"x": xv})
        np.testing.assert_allclose(out, _expected(xv, 1.0), rtol=1e-5)
    finally:
        fleet.stop()


def test_registry_staging_api(tmp_path, mon):
    import paddle_tpu as fluid
    from paddle_tpu.serving import ModelRegistry, publish

    v1 = _save_model(str(tmp_path / "v1"), 1.0)
    v2 = _save_model(str(tmp_path / "v2"), 2.0)
    reg = ModelRegistry(place=fluid.CPUPlace())
    reg.load("m", v1)
    xv = np.ones((2, D_IN), "f4")
    with pytest.raises(ServingError) as ei:
        reg.activate_staged("m")  # nothing staged
    assert ei.value.reason == "model_missing"
    # stage_only runs the FULL ladder but keeps the old version serving
    ver = publish(reg, "m", v2, stage_only=True, warm_buckets=(2,))
    assert reg.staged("m") is ver
    assert reg.models()["m"]["src"] == v1
    reg.activate_staged("m")
    assert reg.models()["m"]["src"] == v2
    assert reg.staged("m") is None
    # discard: never served, old version untouched
    publish(reg, "m", v1, stage_only=True, warm_buckets=(2,))
    assert reg.discard_staged("m") is True
    assert reg.discard_staged("m") is False
    assert reg.models()["m"]["src"] == v2


def test_quarantine_marker_persists_fleet_wide(tmp_path, mon):
    """Satellite: one replica's rejection fast-rejects everywhere.  A
    FRESH registry (a different replica process in fleet terms) must
    refuse the marked snapshot without re-running the ladder."""
    import paddle_tpu as fluid
    from paddle_tpu.serving import (ModelRegistry, QUARANTINE_MARKER,
                                    publish, quarantine_marker)

    v1 = _save_model(str(tmp_path / "v1"), 1.0)
    bad = _save_model(str(tmp_path / "bad"), 2.0, poison_nan=True)
    reg_a = ModelRegistry(place=fluid.CPUPlace())
    reg_a.load("m", v1)
    with pytest.raises(ServingError) as ei:
        publish(reg_a, "m", bad, warm_buckets=(2,))
    assert ei.value.reason == "publish_rejected"
    mk = quarantine_marker(bad)
    assert mk is not None and mk["model"] == "m" and mk["detail"]
    assert os.path.exists(os.path.join(bad, QUARANTINE_MARKER))
    # fresh process (registry B): fast-reject on the persisted marker —
    # the marker message (not the NaN detail a re-run ladder would
    # produce) proves the stage/compile/smoke rungs were skipped
    reg_b = ModelRegistry(place=fluid.CPUPlace())
    reg_b.load("m", v1)
    with pytest.raises(ServingError) as ei:
        publish(reg_b, "m", bad, warm_buckets=(2,))
    assert ei.value.reason == "publish_rejected"
    assert "persisted quarantine marker" in str(ei.value)
    assert reg_b.models()["m"]["src"] == v1


def test_perf_report_fleet_gates(tmp_path):
    """--min-healthy-replicas and --check-roll-convergence over crafted
    streams: healthy passes, sick fails, counters-only OK, zero-evidence
    fails."""
    def write(name, recs):
        p = str(tmp_path / name)
        with open(p, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        return p

    snap = {"kind": "snapshot", "ts": 1.0,
            "counters": {"serving.fleet.requests": 10,
                         "serving.fleet.completed": 10,
                         "serving.fleet.errors": 0},
            "gauges": {"serving.fleet.healthy_replicas": 2.0,
                       "serving.fleet.size": 2.0}}
    ok = write("ok.jsonl", [snap])
    assert perf_report.check(ok, min_healthy_replicas=2,
                             check_roll_convergence=True) == 0
    assert perf_report.check(ok, min_healthy_replicas=3) == 1
    # roll halted with no convergence -> fail; with rolled_back -> pass
    halted = write("halted.jsonl", [
        snap,
        {"kind": "fleet_event", "action": "roll_started", "ctl": "roll-1"},
        {"kind": "fleet_event", "action": "roll_halted", "ctl": "roll-1"},
    ])
    assert perf_report.check(halted, check_roll_convergence=True) == 1
    converged = write("converged.jsonl", [
        snap,
        {"kind": "fleet_event", "action": "roll_started", "ctl": "roll-1"},
        {"kind": "fleet_event", "action": "roll_halted", "ctl": "roll-1"},
        {"kind": "fleet_event", "action": "roll_rolled_back",
         "ctl": "roll-1"},
    ])
    assert perf_report.check(converged, check_roll_convergence=True) == 0
    # counters-only file (no fleet_event records): the events[*] balance
    counters_ok = write("counters_ok.jsonl", [{
        "kind": "snapshot", "ts": 1.0,
        "counters": {"serving.fleet.events[roll_halted]": 1,
                     "serving.fleet.events[roll_rolled_back]": 1},
        "gauges": {}}])
    assert perf_report.check(counters_ok, check_roll_convergence=True) == 0
    counters_bad = write("counters_bad.jsonl", [{
        "kind": "snapshot", "ts": 1.0,
        "counters": {"serving.fleet.events[roll_halted]": 2,
                     "serving.fleet.events[roll_rolled_back]": 1},
        "gauges": {}}])
    assert perf_report.check(counters_bad, check_roll_convergence=True) == 1
    # zero evidence must not gate green
    empty = write("empty.jsonl", [])
    assert perf_report.check(empty, min_healthy_replicas=1) == 1
    assert perf_report.check(empty, check_roll_convergence=True) == 1


def test_serve_trace_fleet_check_crafted(tmp_path):
    """Fleet reconciliation over crafted dirs: a router ledger that the
    replica ledgers contradict fails; an empty dir fails."""
    def fleet_dir(name, router_recs, replica_counters):
        root = tmp_path / name / "telemetry"
        os.makedirs(root / "i1")
        with open(root / "router.jsonl", "w") as f:
            for r in router_recs:
                f.write(json.dumps(r) + "\n")
        for rank, counters in replica_counters.items():
            with open(root / "i1" / f"metrics.p{rank}.jsonl", "w") as f:
                f.write(json.dumps({"kind": "snapshot",
                                    "counters": counters,
                                    "gauges": {}}) + "\n")
        return str(tmp_path / name)

    rsnap = {"kind": "snapshot",
             "counters": {"serving.fleet.requests": 4,
                          "serving.fleet.completed": 4,
                          "serving.fleet.errors": 0}, "gauges": {}}
    good = fleet_dir("good", [rsnap],
                     {0: {"serving.completed": 2},
                      1: {"serving.completed": 2}})
    assert serve_trace.fleet_check(good) == 0
    # replicas claim MORE completions than the router saw, with no
    # replica_down losses to explain them -> overcount, fail
    over = fleet_dir("over", [rsnap],
                     {0: {"serving.completed": 9},
                      1: {"serving.completed": 2}})
    assert serve_trace.fleet_check(over) == 1
    # replicas claim fewer with NO death on record -> undercount, fail
    under = fleet_dir("under", [rsnap],
                      {0: {"serving.completed": 1},
                       1: {"serving.completed": 2}})
    assert serve_trace.fleet_check(under) == 1
    # same undercount WITH a replica death on record -> allowed (the
    # corpse's final snapshot is legitimately stale)
    dead = fleet_dir("dead", [
        rsnap, {"kind": "fleet_event", "action": "replica_dead",
                "rank": 0}],
        {0: {"serving.completed": 1}, 1: {"serving.completed": 2}})
    assert serve_trace.fleet_check(dead) == 0
    empty = str(tmp_path / "empty")
    os.makedirs(os.path.join(empty, "telemetry"))
    assert serve_trace.fleet_check(empty) == 1


# --------------------------------------------------------------------------
# multi-process chaos
# --------------------------------------------------------------------------

def test_fleet_kill_replica_under_load(tmp_path, mon):
    """SIGKILL one of two replicas mid-load: only its in-flight requests
    fail (classified replica_down), traffic redistributes, the
    supervisor restarts it, and every ledger reconciles."""
    v1 = _save_model(str(tmp_path / "m_v1"), 1.0)
    fleet = ServingFleet({"m": v1}, n_replicas=2,
                         root=str(tmp_path / "fleet"),
                         max_restarts=2, **FLEET_KW)
    try:
        fleet.wait_healthy(timeout=120)
        oks, errs = [], []

        def load(n):
            for _ in range(n):
                xv = np.random.rand(2, D_IN).astype("f4")
                try:
                    (out,) = fleet.infer("m", {"x": xv})
                    np.testing.assert_allclose(out, _expected(xv),
                                               rtol=1e-5)
                    oks.append(1)
                except ServingError as e:
                    errs.append(e.reason)
                time.sleep(0.01)

        threads = [threading.Thread(target=load, args=(40,))
                   for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.15)
        with fleet._lock:
            victim = fleet._replicas[0]["proc"]
        victim.send_signal(signal.SIGKILL)
        for t in threads:
            t.join()
        # only the victim's in-flight requests were lost, all classified
        assert all(r == "replica_down" for r in errs), errs
        assert len(errs) <= fleet.router.inflight_cap + 1, \
            f"lost {len(errs)} requests to one replica death"
        assert len(oks) >= 100  # the survivor carried the load
        s = fleet.stats()
        assert s["requests"] == s["completed"] + s["errors"]  # exact
        assert s["completed"] == len(oks) and s["errors"] == len(errs)
        assert s["routed"].get(1, 0) > 0  # traffic reached the survivor
        # the supervisor noticed and restarted the corpse
        _wait_event(fleet, "replica_dead")
        _wait_event(fleet, "replica_restarted")
        fleet.wait_healthy(timeout=120)
        (out,) = fleet.infer("m", {"x": np.ones((2, D_IN), "f4")})
    finally:
        fleet.stop()
    # post-run: the merged fleet view reconciles and the health gate holds
    assert serve_trace.fleet_check(fleet.root) == 0
    router_log = os.path.join(fleet.root, "telemetry", "router.jsonl")
    assert perf_report.check(router_log, min_healthy_replicas=2,
                             check_roll_convergence=True) == 0


def test_fleet_roll_halts_on_store_faults(tmp_path, mon):
    """Rolling publish vs a sick store: rotted content (NaN weights) and
    persistent EIO both halt the roll mid-fleet, the fleet converges
    back on last good EVERYWHERE, zero requests are served by the bad
    version, and a second attempt at the rotted source fast-rejects on
    the persisted quarantine marker."""
    from paddle_tpu.serving.publisher import PUBLISH_IO_ATTEMPTS

    v1 = _save_model(str(tmp_path / "m_v1"), 1.0)
    bad_rot = _save_model(str(tmp_path / "m_rot"), 3.0, poison_nan=True)
    bad_eio = _save_model(str(tmp_path / "m_eio"), 4.0)
    v2 = _save_model(str(tmp_path / "m_v2"), 2.0)
    # rank 1's store access to the eio snapshot fails persistently: each
    # entry fires on its Nth matching op, and each failed attempt aborts
    # after one matching read, so indices 0..N cover every retry the
    # publish budget allows
    eio_spec = ";".join(f"eio@{i}:*m_eio*"
                        for i in range(PUBLISH_IO_ATTEMPTS + 3))
    fleet = ServingFleet(
        {"m": v1}, n_replicas=2, root=str(tmp_path / "fleet"),
        per_rank_env={1: {"FLAGS_fault_spec": eio_spec}}, **FLEET_KW)
    try:
        fleet.wait_healthy(timeout=120)
        xv = np.random.rand(2, D_IN).astype("f4")

        # arm 1: rotted content -> publish_rejected on the NaN rung
        with pytest.raises(ServingError) as ei:
            fleet.rolling_publish("m", bad_rot)
        assert ei.value.reason == "roll_halted"
        assert ei.value.__cause__.reason == "publish_rejected"
        # arm 1b: the rejection persisted a marker next to the snapshot.
        # Restart rank 0 (fresh process: empty in-memory quarantine set)
        # and retry — the NEW process fast-rejects on the PERSISTED
        # marker, proving the verdict survives the replica that made it
        with fleet._lock:
            victim = fleet._replicas[0]["proc"]
        victim.send_signal(signal.SIGKILL)
        _wait_event(fleet, "replica_restarted")
        fleet.wait_healthy(timeout=120)
        with pytest.raises(ServingError) as ei:
            fleet.rolling_publish("m", bad_rot)
        assert ei.value.reason == "roll_halted"
        assert "persisted quarantine marker" in str(ei.value.__cause__)

        # arm 2: persistent EIO on rank 1 -> halts AFTER rank 0 staged;
        # convergence must discard rank 0's staged slot too
        with pytest.raises(ServingError) as ei:
            fleet.rolling_publish("m", bad_eio)
        assert ei.value.reason == "roll_halted"
        assert ei.value.__cause__.reason == "publish_io"

        # the fleet converged on last good everywhere: every replica
        # still serves v1, bit-identically
        actives = fleet.active_versions("m")
        assert len(actives) == 2
        assert all(a["src"] == v1 for a in actives.values()), actives
        for _ in range(6):
            (out,) = fleet.infer("m", {"x": xv})
            np.testing.assert_allclose(out, _expected(xv), rtol=1e-5)
        # a CLEAN roll still goes through after both halts
        fleet.rolling_publish("m", v2)
        (out,) = fleet.infer("m", {"x": xv})
        np.testing.assert_allclose(out, _expected(xv, 2.0), rtol=1e-5)
        actives = fleet.active_versions("m")
        assert all(a["src"] == v2 for a in actives.values()), actives
        # roll episodes on the wire: 3 halted+rolled_back, 1 converged
        assert len(_router_events(fleet, "roll_halted")) == 3
        assert len(_router_events(fleet, "roll_rolled_back")) == 3
        assert len(_router_events(fleet, "roll_converged")) == 1
    finally:
        fleet.stop()
    assert serve_trace.fleet_check(fleet.root) == 0
    router_log = os.path.join(fleet.root, "telemetry", "router.jsonl")
    assert perf_report.check(router_log, min_healthy_replicas=2,
                             check_roll_convergence=True) == 0


def test_fleet_sigterm_drains_without_shedding(tmp_path, mon):
    """SIGTERM one replica under load: it drains (in-flight served out,
    exit 0, retired — not restarted), the router stops dispatching to it
    before the shutdown could shed anything, and no request fails."""
    v1 = _save_model(str(tmp_path / "m_v1"), 1.0)
    fleet = ServingFleet({"m": v1}, n_replicas=2,
                         root=str(tmp_path / "fleet"), **FLEET_KW)
    try:
        fleet.wait_healthy(timeout=120)
        failures = []
        done = threading.Event()

        def load():
            while not done.is_set():
                xv = np.random.rand(2, D_IN).astype("f4")
                try:
                    (out,) = fleet.infer("m", {"x": xv})
                    np.testing.assert_allclose(out, _expected(xv),
                                               rtol=1e-5)
                except ServingError as e:
                    failures.append(e.reason)
                time.sleep(0.01)

        threads = [threading.Thread(target=load) for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        with fleet._lock:
            victim = fleet._replicas[0]["proc"]
        victim.send_signal(signal.SIGTERM)
        victim.wait(timeout=60)
        assert victim.returncode == 0  # deliberate drain, clean exit
        time.sleep(0.3)  # a few more load iterations on the shrunk fleet
        done.set()
        for t in threads:
            t.join()
        # the drain shed NOTHING: every request completed
        assert failures == [], failures
        s = fleet.stats()
        assert s["requests"] == s["completed"] and s["errors"] == 0
        _wait_event(fleet, "replica_retired")
        # retired is final: no restart of a deliberately drained replica
        assert not _router_events(fleet, "replica_restarted")
    finally:
        fleet.stop()
    # the drained replica's own final on-disk ledger agrees nothing was
    # shed or dropped at shutdown
    tel = os.path.join(fleet.root, "telemetry")
    victim_counters = {}
    for dirpath, _, names in os.walk(tel):
        for n in names:
            if n != "metrics.p0.jsonl":
                continue
            with open(os.path.join(dirpath, n)) as f:
                for ln in f:
                    rec = json.loads(ln)
                    if rec.get("kind") == "snapshot":
                        victim_counters = rec.get("counters", {})
    assert victim_counters.get("serving.shed", 0) == 0
    assert victim_counters.get("serving.shutdowns", 0) == 0
    assert victim_counters.get("serving.completed", 0) > 0
    assert serve_trace.fleet_check(fleet.root) == 0


def test_fleet_roll_resumes_from_persisted_state(tmp_path, mon):
    """Supervisor crash mid-roll: a fresh supervisor (same fleet root)
    finishes the roll from ROLL.json — verified ranks are not re-staged,
    the activate phase completes, ACTIVE.json moves."""
    v1 = _save_model(str(tmp_path / "m_v1"), 1.0)
    v2 = _save_model(str(tmp_path / "m_v2"), 2.0)
    fleet = ServingFleet({"m": v1}, n_replicas=1,
                         root=str(tmp_path / "fleet"), **FLEET_KW)
    try:
        fleet.wait_healthy(timeout=120)
        # stage phase ran, then the supervisor "crashed" before activate
        reply = fleet._control_rpc(0, {"op": "stage", "model": "m",
                                       "src": v2})
        assert reply.get("ok"), reply
        fleet._persist_roll({"model": "m", "src": v2, "ctl": "roll-x",
                             "phase": "activate", "verified": [0],
                             "acked": [], "last_good": v1})
        roll = fleet.resume_roll()
        assert roll["phase"] == "done" and roll["acked"] == [0]
        xv = np.ones((2, D_IN), "f4")
        (out,) = fleet.infer("m", {"x": xv})
        np.testing.assert_allclose(out, _expected(xv, 2.0), rtol=1e-5)
        active = json.load(open(os.path.join(fleet.root, "ACTIVE.json")))
        assert active["models"]["m"]["src"] == v2
        assert _router_events(fleet, "roll_resumed")
        # nothing left to resume now
        assert fleet.resume_roll() is None
    finally:
        fleet.stop()
