"""Stream-state protocol (ISSUE 5): every reader combinator and source
grows state_dict()/load_state_dict(), resume is an O(1) seek that is
bit-identical even for shuffled sources, and the resilient loop stores
the stream state in RESUME.json so preemption/rollback resume never
replays the dataset.  CPU-only, deterministic — tier-1."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor, recordio
from paddle_tpu import reader as rd
from paddle_tpu.checkpoint_manager import CheckpointManager
from paddle_tpu.faults import FaultInjector
from paddle_tpu.reader import is_checkpointable

FAST = dict(backoff_base_s=0.0)


def _write_rio(tmp_path, n=24, dim=3, chunk=4, name="s.rio"):
    p = str(tmp_path / name)
    recordio.write_arrays(
        p, [(np.full(dim, i, "f4"),) for i in range(n)], max_chunk_records=chunk)
    return p


def _drain_resume(reader_obj, k):
    """Pull k items, snapshot, rebuild from state, return (head, tail)."""
    it = iter(reader_obj())
    head = [next(it) for _ in range(k)]
    state = reader_obj.state_dict()
    return head, state


# --- per-combinator state round-trips ---------------------------------------

def test_recordio_reader_state_roundtrip(tmp_path):
    p = _write_rio(tmp_path)
    r = recordio.reader_creator(p)
    assert is_checkpointable(r)
    head, state = _drain_resume(r, 10)
    r2 = recordio.reader_creator(p)
    r2.load_state_dict(state)
    tail = [s[0][0] for s in r2()]
    assert tail == list(range(10, 24))


def test_shuffle_reshuffles_per_epoch_deterministically():
    """The satellite golden test: same seed => same schedule across
    reconstructions, but epoch k and epoch k+1 permute differently."""
    def src():
        yield from range(30)

    s = rd.shuffle(src, 10, seed=42)
    e0, e1 = list(s()), list(s())
    assert sorted(e0) == sorted(e1) == list(range(30))
    assert e0 != e1, "epochs must reshuffle differently"
    s2 = rd.shuffle(src, 10, seed=42)
    assert list(s2()) == e0 and list(s2()) == e1, \
        "the epoch schedule must be deterministic under the same seed"


def test_shuffle_state_resume_bit_identical(tmp_path):
    p = _write_rio(tmp_path)
    sh = rd.shuffle(recordio.reader_creator(p), 8, seed=5)
    assert is_checkpointable(sh)
    full = [s[0][0] for s in sh()]          # epoch 0, uninterrupted

    sh2 = rd.shuffle(recordio.reader_creator(p), 8, seed=5)
    it = iter(sh2())
    head = [next(it)[0][0] for _ in range(11)]  # mid-buffer position
    state = sh2.state_dict()
    sh3 = rd.shuffle(recordio.reader_creator(p), 8, seed=5)
    sh3.load_state_dict(state)
    tail = [s[0][0] for s in sh3()]
    assert head + tail == full, "shuffled resume must be bit-identical"


def test_batch_chain_map_firstn_cache_state(tmp_path):
    p1 = _write_rio(tmp_path, n=10, name="a.rio")
    p2 = _write_rio(tmp_path, n=10, name="b.rio")

    # batch over chain, interrupted across the file boundary
    ch = rd.chain(recordio.reader_creator(p1), recordio.reader_creator(p2))
    b = rd.batch(ch, 3, drop_last=False)
    assert is_checkpointable(b)
    it = iter(b())
    head = [next(it) for _ in range(4)]     # 12 samples: into the 2nd file
    state = b.state_dict()
    ch2 = rd.chain(recordio.reader_creator(p1), recordio.reader_creator(p2))
    b2 = rd.batch(ch2, 3, drop_last=False)
    b2.load_state_dict(state)
    tail = list(b2())
    got = [s[0][0] for batch in head + tail for s in batch]
    assert got == list(range(10)) + list(range(10))

    # map + firstn
    m = rd.firstn(rd.map_readers(lambda s: s[0] * 2, recordio.reader_creator(p1)), 7)
    it = iter(m())
    head = [next(it)[0] for _ in range(4)]
    state = m.state_dict()
    m2 = rd.firstn(rd.map_readers(lambda s: s[0] * 2, recordio.reader_creator(p1)), 7)
    m2.load_state_dict(state)
    # review regression: state_dict after load (before iterating) must
    # report the LOADED yielded count, not a stale live one — this is
    # exactly what the resilient loop snapshots before its first pull
    assert m2.state_dict()["yielded"] == 4
    tail = [a[0] for a in m2()]
    assert head + tail == [0.0, 2.0, 4.0, 6.0, 8.0, 10.0, 12.0]

    # cache: O(1) index state even over a non-checkpointable source
    def plain():
        yield from range(9)

    c = rd.cache(plain)
    assert is_checkpointable(c)
    it = iter(c())
    head = [next(it) for _ in range(5)]
    c2_state = c.state_dict()
    c.load_state_dict(c2_state)
    assert head + list(c()) == list(range(9))


def test_xmap_ordered_state_resume(tmp_path):
    p = _write_rio(tmp_path, n=16)
    x = rd.xmap_readers(lambda s: s[0][0] * 10, recordio.reader_creator(p),
                        2, 4, order=True)
    assert is_checkpointable(x)
    it = iter(x())
    head = [next(it) for _ in range(6)]
    state = x.state_dict()
    x2 = rd.xmap_readers(lambda s: s[0][0] * 10, recordio.reader_creator(p),
                         2, 4, order=True)
    x2.load_state_dict(state)
    tail = list(x2())
    assert head + tail == [float(i * 10) for i in range(16)]
    # unordered xmap is honest about being non-resumable
    xu = rd.xmap_readers(lambda s: s, recordio.reader_creator(p), 2, 4)
    assert not is_checkpointable(xu)
    with pytest.raises(TypeError, match="not checkpointable"):
        xu.state_dict()


def test_stateless_source_is_not_checkpointable():
    def plain():
        yield from range(5)

    assert not is_checkpointable(plain)
    assert not is_checkpointable(rd.batch(plain, 2))
    with pytest.raises(TypeError, match="not checkpointable"):
        rd.batch(plain, 2).state_dict()


def test_dataloader_state_tracks_consumer_not_producer(tmp_path):
    """The producer prefetches ahead; state_dict must reflect what the
    CONSUMER saw, so in-flight prefetched batches are re-staged on resume."""
    p = _write_rio(tmp_path, n=20, dim=4)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")

    def make_loader():
        gen = rd.map_readers(
            lambda batch: {"x": np.stack([s[0] for s in batch])},
            rd.batch(recordio.reader_creator(p), 2, drop_last=True))
        return fluid.DataLoader.from_generator([x], capacity=4) \
            .set_batch_generator(gen)

    loader = make_loader()
    assert loader.checkpointable()
    it = iter(loader)
    head = [np.asarray(next(it)["x"]) for _ in range(3)]
    state = loader.state_dict()   # produced may be ahead; consumed == 3
    loader2 = make_loader()
    loader2.load_state_dict(state)
    tail = [np.asarray(b["x"]) for b in loader2]
    got = np.concatenate([a[:, 0] for a in head + tail])
    np.testing.assert_array_equal(got, np.arange(20, dtype="f4"))


def test_dataset_state(tmp_path):
    p = str(tmp_path / "ds.rio")
    recordio.write_arrays(
        p, [(np.full(2, i, "f4"), np.asarray([i], "i8")) for i in range(12)],
        max_chunk_records=5)
    ds = fluid.InMemoryDataset()
    ds.set_batch_size(2)
    ds.set_filelist([p])
    ds.set_use_var(["a", "b"])
    ds.load_into_memory()
    assert is_checkpointable(ds)
    it = iter(ds.batches())
    head = [next(it) for _ in range(3)]
    state = ds.state_dict()
    assert state["samples_consumed"] == 6
    ds2 = fluid.InMemoryDataset()
    ds2.set_batch_size(2)
    ds2.set_filelist([p])
    ds2.set_use_var(["a", "b"])
    ds2.load_into_memory()
    ds2.load_state_dict(state)
    tail = list(ds2.batches())
    ids = [int(v) for b in head + tail for v in b["b"].reshape(-1)]
    assert ids == list(range(12))


def test_slot_batch_reader_state(tmp_path):
    p = str(tmp_path / "slots.rio")
    recordio.write_arrays(
        p, [(np.full(3, i, "f4"), np.asarray([i], "i4")) for i in range(12)],
        max_chunk_records=4)
    r = recordio.SlotBatchReader([p], 2, n_threads=1)
    assert is_checkpointable(r)
    it = iter(r)
    head = [next(it) for _ in range(2)]
    state = r.state_dict()
    r.close()
    r2 = recordio.SlotBatchReader([p], 2, n_threads=1)
    r2.load_state_dict(state)
    tail = list(iter(r2))
    r2.close()
    ids = [int(v) for b in head + tail for v in b[1].reshape(-1)]
    assert ids == list(range(12))
    # multi-threaded order is irreproducible -> honestly not checkpointable
    r3 = recordio.SlotBatchReader([p, p], 2, n_threads=2)
    assert not is_checkpointable(r3)
    r3.close()


# --- the acceptance criterion: O(1) resume over shuffle(recordio) -----------

def _build_model(seed=11):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, 8, act="relu")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
        fluid.optimizer.Adam(1e-2).minimize(loss)
    startup.random_seed = seed
    main.random_seed = seed
    return main, startup, loss


def _params(scope):
    return {n: np.asarray(scope.find_var(n)).copy()
            for n in scope.local_var_names()}


def _rio_factory(path, batch=4):
    def to_feed(samples):
        xv = np.stack([s[0] for s in samples]).astype("f4")
        return {"x": xv, "y": xv.sum(1, keepdims=True)}

    def factory():
        return rd.map_readers(
            to_feed,
            rd.batch(rd.shuffle(recordio.reader_creator(path), 8, seed=3),
                     batch, drop_last=True))

    return factory


def test_preempt_resume_over_shuffled_recordio_is_o1_and_bit_identical(tmp_path):
    """ISSUE 5 acceptance: preemption + resume of a run over a
    shuffle(recordio) source is bit-identical to an uninterrupted run
    WITHOUT replaying from batch 0 — fast-forward batch count must be 0
    (the stream seeks) and the seek counter must fire."""
    p = _write_rio(tmp_path, n=48, dim=4, chunk=6)
    main, startup, loss = _build_model()
    factory = _rio_factory(p)

    # reference: uninterrupted
    exe = fluid.Executor(fluid.CPUPlace())
    ref_scope = fluid.Scope()
    exe.run(startup, scope=ref_scope)
    ref_stats = fluid.resilient_train_loop(
        exe, main, factory, [loss], scope=ref_scope, max_inflight=3)
    ref = _params(ref_scope)

    # interrupted at step 5
    exe1 = fluid.Executor(fluid.CPUPlace())
    scope1 = fluid.Scope()
    exe1.run(startup, scope=scope1)
    cm = CheckpointManager(str(tmp_path / "ckpt"), program=main, scope=scope1)
    stats1 = fluid.resilient_train_loop(
        exe1, main, _rio_factory(p), [loss], scope=scope1,
        injector=FaultInjector("preempt@5"), checkpoint_manager=cm,
        max_inflight=3)
    assert stats1.preempted and stats1.resume_step == 5
    with open(os.path.join(stats1.checkpoint_dir, "RESUME.json")) as f:
        info = json.load(f)
    assert "stream_state" in info, "checkpoint must carry the stream state"

    # fresh process: restore + O(1) seek
    exe2 = fluid.Executor(fluid.CPUPlace())
    scope2 = fluid.Scope()
    exe2.run(startup, scope=scope2)
    cm2 = CheckpointManager(str(tmp_path / "ckpt"), program=main, scope=scope2)
    monitor.reset()
    monitor.enable()
    try:
        stats2 = fluid.resilient_train_loop(
            exe2, main, _rio_factory(p), [loss], scope=scope2,
            checkpoint_manager=cm2, resume=True, max_inflight=3)
    finally:
        counters = monitor.get_monitor().counter_values()
        monitor.disable()
    assert stats2.steps == ref_stats.steps
    assert counters.get("resilience.stream_seek", 0) == 1
    assert counters.get("resilience.replayed_batches", 0) == 0, \
        "stateful resume must not replay a single batch"
    assert counters.get("resilience.replay_fallback", 0) == 0
    for n, v in ref.items():
        np.testing.assert_array_equal(
            np.asarray(scope2.find_var(n)), v,
            err_msg=f"state var {n} diverged after stream-state resume")


def test_rollback_uses_stream_state(tmp_path):
    """nan_mode='rollback' over a shuffled recordio source: the restored
    checkpoint's stream state rewinds the shuffle mid-epoch, and the end
    state matches the uninterrupted run bit-for-bit."""
    p = _write_rio(tmp_path, n=48, dim=4, chunk=6)
    main, startup, loss = _build_model()

    exe = fluid.Executor(fluid.CPUPlace())
    ref_scope = fluid.Scope()
    exe.run(startup, scope=ref_scope)
    fluid.resilient_train_loop(
        exe, main, _rio_factory(p), [loss], scope=ref_scope, max_inflight=3)
    ref = _params(ref_scope)

    exe1 = fluid.Executor(fluid.CPUPlace())
    scope1 = fluid.Scope()
    exe1.run(startup, scope=scope1)
    cm = CheckpointManager(str(tmp_path / "ck2"), program=main, scope=scope1,
                           save_every_steps=3)
    monitor.reset()
    monitor.enable()
    try:
        stats = fluid.resilient_train_loop(
            exe1, main, _rio_factory(p), [loss], scope=scope1,
            injector=FaultInjector("nan@7"), nan_mode="rollback",
            checkpoint_manager=cm, policy=fluid.RetryPolicy(**FAST),
            max_inflight=3)
        counters = monitor.get_monitor().counter_values()
    finally:
        monitor.disable()
    assert stats.rollbacks == 1
    assert counters.get("resilience.stream_seek", 0) == 1
    assert counters.get("resilience.replayed_batches", 0) == 0
    for n, v in ref.items():
        np.testing.assert_array_equal(np.asarray(scope1.find_var(n)), v,
                                      err_msg=f"{n} diverged after rollback")


# --- stateless fallback: loud + divergence-guarded --------------------------

def _feeds(n, batch=8):
    rng = np.random.RandomState(0)
    out = []
    for _ in range(n):
        xv = rng.rand(batch, 4).astype("f4")
        out.append({"x": xv, "y": xv.sum(1, keepdims=True)})
    return out


def test_stateless_resume_replays_loudly(tmp_path):
    """A plain-list factory (no stream state) still resumes, but the
    fast-forward is visible: replay_fast_forward event with the batch
    count + resilience.replayed_batches counter (what perf_report's
    --max-replay-batches gates on)."""
    main, startup, loss = _build_model()
    feeds = _feeds(12)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope)
    stats = fluid.resilient_train_loop(
        exe, main, lambda: list(feeds), [loss], scope=scope,
        injector=FaultInjector("preempt@5"), checkpoint_manager=cm,
        max_inflight=3)
    assert stats.preempted

    exe2 = fluid.Executor(fluid.CPUPlace())
    scope2 = fluid.Scope()
    exe2.run(startup, scope=scope2)
    cm2 = CheckpointManager(str(tmp_path), program=main, scope=scope2)
    monitor.reset()
    monitor.enable()
    try:
        stats2 = fluid.resilient_train_loop(
            exe2, main, lambda: list(feeds), [loss], scope=scope2,
            checkpoint_manager=cm2, resume=True, max_inflight=3)
        counters = monitor.get_monitor().counter_values()
        events = [r for r in monitor.step_records()
                  if r.get("kind") == "resilience_event"
                  and r.get("action") == "replay_fast_forward"]
    finally:
        monitor.disable()
    assert stats2.steps == 12
    assert counters.get("resilience.replay_fallback", 0) == 1
    assert counters.get("resilience.replayed_batches", 0) == 5
    assert len(events) == 1 and events[0]["batches"] == 5


def test_replay_divergence_raises_clear_error(tmp_path):
    """A factory whose replay yields a DIFFERENT batch than the replay
    window recorded must raise, not silently train on different data."""
    main, startup, loss = _build_model()
    feeds = _feeds(10)
    calls = {"n": 0}

    def flaky_factory():
        calls["n"] += 1
        if calls["n"] == 1:
            return list(feeds)
        mutated = [dict(f) for f in feeds]
        mutated[4] = {"x": mutated[4]["x"] + 1.0, "y": mutated[4]["y"]}
        return mutated

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope,
                           save_every_steps=3)
    with pytest.raises(RuntimeError, match="replay divergence"):
        fluid.resilient_train_loop(
            exe, main, flaky_factory, [loss], scope=scope,
            injector=FaultInjector("nan@5"), nan_mode="rollback",
            checkpoint_manager=cm, policy=fluid.RetryPolicy(**FAST),
            max_inflight=3)


def test_resume_sidecar_name_is_rank_namespaced():
    """Review regression: coordinated checkpoints share one pending dir;
    a fixed RESUME.json would let the last rank clobber every other
    rank's stream cursor."""
    from paddle_tpu.resilience import RESUME_FILE, resume_sidecar_name

    assert resume_sidecar_name(0, 1) == RESUME_FILE
    assert resume_sidecar_name(0, 2) == "RESUME.p0.json"
    assert resume_sidecar_name(3, 4) == "RESUME.p3.json"
    assert len({resume_sidecar_name(r, 8) for r in range(8)}) == 8


def test_perf_report_replay_and_corrupt_gates(tmp_path):
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent.parent))
    from tools.perf_report import check, data_corrupt_fraction, replayed_batches

    rows = [{"kind": "step", "recompiles_total": 0} for _ in range(6)]
    rows += [{"kind": "resilience_event", "action": "replay_fast_forward",
              "class": "DataStream", "at_batch": 5, "batches": 5}]
    rows += [{"kind": "snapshot",
              "counters": {"data.corrupt_chunks": 1,
                           "data.chunks_scanned": 50}}]
    assert replayed_batches(rows) == 5
    assert data_corrupt_fraction(rows) == pytest.approx(0.02)
    path = tmp_path / "m.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert check(str(path), max_replay_batches=5) == 0
    assert check(str(path), max_replay_batches=0) == 1
    assert check(str(path), max_data_corrupt_frac=0.05) == 0
    assert check(str(path), max_data_corrupt_frac=0.01) == 1
    # counters-only file (loader-side): data gates still checkable
    bare = tmp_path / "bare.jsonl"
    bare.write_text(json.dumps(rows[-1]) + "\n")
    assert check(str(bare), max_data_corrupt_frac=0.05) == 0
