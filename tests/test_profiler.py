"""Profiler: per-run aggregate, per-op attribution, Chrome-trace export
(reference fluid.profiler + tools/timeline.py)."""
import json
import os

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import profiler


def _model():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, 16, act="relu")
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def test_per_run_table_and_context_manager(capsys):
    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    profiler.reset_profiler()
    with profiler.profiler(sorted_key="total"):
        for _ in range(3):
            exe.run(main, feed={"x": np.ones((4, 8), "f4"), "y": np.ones((4, 1), "f4")},
                    fetch_list=[loss], scope=scope)
    out = capsys.readouterr().out
    assert "executor.run" in out and "Calls" in out


def test_per_op_attribution_and_chrome_trace(tmp_path):
    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    profiler.reset_profiler()
    profiler.start_profiler()
    table = profiler.profile_program(
        main, feed={"x": np.ones((4, 8), "f4"), "y": np.ones((4, 1), "f4")},
        scope=scope, repeat=2)
    profiler.stop_profiler(profile_path=str(tmp_path / "tbl.txt"))
    assert "mul" in table and "Avg(ms)" in table

    trace = str(tmp_path / "trace.json")
    n = profiler.export_chrome_trace(trace)
    assert n > 0
    doc = json.load(open(trace))
    names = {e["name"] for e in doc["traceEvents"]}
    assert "mul" in names and "square_error_cost" in names

    # multi-process merge gives distinct pid lanes
    merged = str(tmp_path / "merged.json")
    profiler.merge_chrome_traces({"trainer0": trace, "trainer1": trace}, merged)
    doc = json.load(open(merged))
    pids = {e["pid"] for e in doc["traceEvents"]}
    assert pids == {0, 1}
