"""Sparse (SelectedRows) embedding gradients + DeepFM CTR path.

Reference: framework/selected_rows.h:32 + selected_rows_functor.cc MergeAdd
+ per-optimizer sparse kernels; dist_ctr.py model shape.  The contract
tested here: an is_sparse embedding never produces a dense V×D gradient —
the backward yields (rows, values) slabs and the optimizer touches only
those rows."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.core import lowering
from paddle_tpu.core.selected_rows import SelectedRows
from paddle_tpu.models import deepfm


def test_selected_rows_merged_golden():
    rows = jnp.asarray([5, 2, 5, 9, 2, 2], dtype=jnp.int32)
    vals = jnp.arange(12, dtype=jnp.float32).reshape(6, 2)
    sr = SelectedRows(rows, vals, height=10)
    dense = np.zeros((10, 2), "float32")
    for r, v in zip(np.asarray(rows), np.asarray(vals)):
        dense[r] += v
    m = sr.merged()
    mr = np.asarray(m.rows)
    # merged: unique rows present once, rest sentinel == height
    uniq = sorted(set(np.asarray(rows).tolist()))
    assert sorted(r for r in mr if r < 10) == uniq
    np.testing.assert_allclose(np.asarray(m.to_dense()), dense, atol=1e-6)
    np.testing.assert_allclose(np.asarray(sr.to_dense()), dense, atol=1e-6)


def _embedding_model(is_sparse, opt_name, vocab=50, dim=4, seed=3):
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", [3], dtype="int64")
        label = fluid.layers.data("label", [1], dtype="float32")
        emb = fluid.layers.embedding(
            ids, size=[vocab, dim], is_sparse=is_sparse,
            param_attr=fluid.ParamAttr(name=f"tbl_{is_sparse}_{opt_name}"),
        )
        flat = fluid.layers.reshape(emb, [-1, 3 * dim])
        pred = fluid.layers.fc(flat, 1, param_attr=fluid.ParamAttr(name=f"fcw_{is_sparse}_{opt_name}"))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, label))
        opt = {"sgd": fluid.optimizer.SGD(0.1),
               "adagrad": fluid.optimizer.Adagrad(0.1),
               "momentum": fluid.optimizer.Momentum(0.1, 0.9),
               "adam": fluid.optimizer.Adam(0.05)}[opt_name]
        opt.minimize(loss)
    return main, startup, loss, f"tbl_{is_sparse}_{opt_name}"


@pytest.mark.parametrize("opt_name", ["sgd", "adagrad"])
def test_sparse_matches_dense_training(opt_name):
    """SGD/Adagrad sparse updates are numerically identical to dense (a
    zero dense grad row is a no-op for both rules).  Duplicate-heavy ids
    exercise MergeAdd."""
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50, size=(6, 8, 3))
    ids[:, ::2, :] = ids[:, :1, :]  # force heavy duplication
    labels = rng.rand(6, 8, 1).astype("f4")

    losses = {}
    tables = {}
    for is_sparse in (False, True):
        main, startup, loss, tbl = _embedding_model(is_sparse, opt_name)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)
        vals = []
        for i in range(6):
            (lv,) = exe.run(main, feed={"ids": ids[i], "label": labels[i]},
                            fetch_list=[loss], scope=scope)
            vals.append(float(np.asarray(lv).reshape(-1)[0]))
        losses[is_sparse] = vals
        tables[is_sparse] = np.asarray(scope.find_var(tbl))
        if is_sparse:
            assert lowering.LAST_TRACE_REPORT["sparse_grad_params"] == [tbl]
        else:
            assert lowering.LAST_TRACE_REPORT["sparse_grad_params"] == []
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(tables[True], tables[False], rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("opt_name", ["momentum", "adam"])
def test_sparse_lazy_semantics(opt_name):
    """Momentum/Adam sparse kernels update only touched rows (reference
    SparseAdamFunctor / SparseMomentumFunctor semantics): untouched rows'
    params AND accumulators stay exactly put, unlike the dense rule."""
    main, startup, loss, tbl = _embedding_model(True, opt_name)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    t0 = np.asarray(scope.find_var(tbl)).copy()
    ids = np.array([[1, 2, 3], [1, 2, 7]], dtype="int64")
    label = np.ones((2, 1), "f4")
    for _ in range(3):
        exe.run(main, feed={"ids": ids, "label": label}, fetch_list=[loss], scope=scope)
    t1 = np.asarray(scope.find_var(tbl))
    touched = sorted(set(ids.reshape(-1).tolist()))
    untouched = [r for r in range(50) if r not in touched]
    np.testing.assert_array_equal(t1[untouched], t0[untouched])
    assert np.abs(t1[touched] - t0[touched]).max() > 1e-6


def test_sparse_grad_with_padding_idx():
    """padding_idx rows must receive zero gradient through the sparse tap."""
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 1
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", [4], dtype="int64")
        label = fluid.layers.data("label", [1], dtype="float32")
        emb = fluid.layers.embedding(ids, size=[20, 4], is_sparse=True, padding_idx=0,
                                     param_attr=fluid.ParamAttr(name="padtbl"))
        pred = fluid.layers.fc(fluid.layers.reshape(emb, [-1, 16]), 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, label))
        fluid.optimizer.SGD(0.5).minimize(loss)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    t0 = np.asarray(scope.find_var("padtbl")).copy()
    ids_v = np.array([[0, 1, 2, 0], [0, 3, 1, 0]], dtype="int64")
    for _ in range(2):
        exe.run(main, feed={"ids": ids_v, "label": np.ones((2, 1), "f4")},
                fetch_list=[loss], scope=scope)
    t1 = np.asarray(scope.find_var("padtbl"))
    np.testing.assert_array_equal(t1[0], t0[0])  # padding row untouched
    assert np.abs(t1[1] - t0[1]).max() > 1e-7


def test_rmsprop_sparse_raises_clearly():
    main, startup, loss, _ = None, None, None, None
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", [2], dtype="int64")
        label = fluid.layers.data("label", [1], dtype="float32")
        emb = fluid.layers.embedding(ids, size=[10, 3], is_sparse=True)
        pred = fluid.layers.fc(fluid.layers.reshape(emb, [-1, 6]), 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, label))
        fluid.optimizer.RMSProp(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    with pytest.raises(NotImplementedError, match="SelectedRows"):
        exe.run(main, feed={"ids": np.zeros((2, 2), "int64"),
                            "label": np.zeros((2, 1), "f4")},
                fetch_list=[loss], scope=scope)


def test_deepfm_trains_sparse():
    main, startup, feeds, fetches = deepfm.build(num_fields=6, vocab_size=200,
                                                 embed_dim=4, mlp_dims=(16, 8),
                                                 learning_rate=0.1)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    # learnable rule: click iff field-0 id is even
    losses = []
    for _ in range(25):
        ids = rng.randint(0, 200, size=(32, 6))
        label = (ids[:, :1] % 2 == 0).astype("f4")
        (lv,) = exe.run(main, feed={"feat_ids": ids, "label": label},
                        fetch_list=[fetches["loss"]], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert sorted(lowering.LAST_TRACE_REPORT["sparse_grad_params"]) == ["deepfm_v", "deepfm_w"]
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.8, losses


def test_deepfm_trains_on_mesh_with_sharded_tables():
    """dp×ep mesh: batch data-parallel, embedding tables row-sharded over ep
    (the distributed-lookup-table capability, SURVEY §2c)."""
    from paddle_tpu.parallel import make_mesh

    main, startup, feeds, fetches = deepfm.build(num_fields=4, vocab_size=64,
                                                 embed_dim=4, mlp_dims=(8,),
                                                 learning_rate=0.1)
    n = fluid.parallel.shard_parameters(main, {"deepfm_w": ("ep", None),
                                               "deepfm_v": ("ep", None)})
    assert n == 2
    mesh = make_mesh((2, 4), ("dp", "ep"))
    compiled = fluid.CompiledProgram(main).with_mesh(mesh)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(1)
    losses = []
    for _ in range(15):
        ids = rng.randint(0, 64, size=(16, 4))
        label = (ids[:, :1] % 2 == 0).astype("f4")
        (lv,) = exe.run(compiled, feed={"feat_ids": ids, "label": label},
                        fetch_list=[fetches["loss"]], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0]
    spec = scope.find_var("deepfm_v").sharding.spec
    assert tuple(spec) == ("ep", None)
