"""Native RecordIO (C++ chunked CRC format, native/recordio.cc) round-trip
+ corruption detection (reference: paddle/fluid/recordio/)."""
import os

import numpy as np
import pytest

from paddle_tpu import recordio


def test_roundtrip_bytes(tmp_path):
    p = str(tmp_path / "r.rio")
    with recordio.Writer(p, max_chunk_records=3) as w:
        for i in range(10):
            w.write(bytes([i]) * (i + 1))
    with recordio.Scanner(p) as s:
        recs = list(s)
    assert len(recs) == 10
    for i, r in enumerate(recs):
        assert r == bytes([i]) * (i + 1)


def test_roundtrip_arrays(tmp_path):
    p = str(tmp_path / "a.rio")
    rng = np.random.RandomState(0)
    samples = [(rng.rand(3, 4).astype("f4"), rng.randint(0, 9, (2,)).astype("i8"))
               for _ in range(7)]
    n = recordio.write_arrays(p, samples, max_chunk_records=2)
    assert n == 7
    back = list(recordio.read_arrays(p))
    assert len(back) == 7
    for (a, b), (a2, b2) in zip(samples, back):
        np.testing.assert_array_equal(a, a2)
        np.testing.assert_array_equal(b, b2)


def test_crc_detects_corruption(tmp_path):
    p = str(tmp_path / "c.rio")
    with recordio.Writer(p) as w:
        w.write(b"hello world" * 10)
    raw = bytearray(open(p, "rb").read())
    raw[-3] ^= 0xFF  # flip a payload byte
    open(p, "wb").write(bytes(raw))
    with pytest.raises(IOError, match="CRC"):
        list(recordio.Scanner(p))


def test_empty_file_is_clean_eof(tmp_path):
    p = str(tmp_path / "e.rio")
    with recordio.Writer(p):
        pass
    assert list(recordio.Scanner(p)) == []


def test_reader_creator_feeds_dataloader(tmp_path):
    """RecordIO as the file backend of the reader stack (reference
    create_recordio_file_reader op role)."""
    p = str(tmp_path / "d.rio")
    rng = np.random.RandomState(1)
    recordio.write_arrays(
        p, [(rng.rand(4).astype("f4"), np.asarray([i], "i8")) for i in range(12)])
    reader = recordio.reader_creator(p)
    got = [s[1][0] for s in reader()]
    assert got == list(range(12))
