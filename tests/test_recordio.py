"""Native RecordIO (C++ chunked CRC format, native/recordio.cc) round-trip
+ corruption detection (reference: paddle/fluid/recordio/) + the ISSUE 5
on-disk robustness matrix: truncated final chunk, flipped byte mid-chunk,
zero-length file, and mixed good/corrupt file lists — each asserting the
exact `data.corrupt_chunks` spend and surviving-sample parity."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor, recordio
from paddle_tpu.errors import DataError


@pytest.fixture
def corrupt_budget():
    """Arm a corrupt budget for the duration of a test, restore strict."""
    def arm(n):
        fluid.set_flags({"FLAGS_data_corrupt_budget": n})
        recordio.reset_corrupt_spent()

    try:
        yield arm
    finally:
        fluid.set_flags({"FLAGS_data_corrupt_budget": 0})


def _write(path, n, chunk=4, dim=3):
    recordio.write_arrays(
        path, [(np.full(dim, i, "f4"),) for i in range(n)],
        max_chunk_records=chunk)
    return path


def test_roundtrip_bytes(tmp_path):
    p = str(tmp_path / "r.rio")
    with recordio.Writer(p, max_chunk_records=3) as w:
        for i in range(10):
            w.write(bytes([i]) * (i + 1))
    with recordio.Scanner(p) as s:
        recs = list(s)
    assert len(recs) == 10
    for i, r in enumerate(recs):
        assert r == bytes([i]) * (i + 1)


def test_roundtrip_arrays(tmp_path):
    p = str(tmp_path / "a.rio")
    rng = np.random.RandomState(0)
    samples = [(rng.rand(3, 4).astype("f4"), rng.randint(0, 9, (2,)).astype("i8"))
               for _ in range(7)]
    n = recordio.write_arrays(p, samples, max_chunk_records=2)
    assert n == 7
    back = list(recordio.read_arrays(p))
    assert len(back) == 7
    for (a, b), (a2, b2) in zip(samples, back):
        np.testing.assert_array_equal(a, a2)
        np.testing.assert_array_equal(b, b2)


def test_crc_detects_corruption(tmp_path):
    p = str(tmp_path / "c.rio")
    with recordio.Writer(p) as w:
        w.write(b"hello world" * 10)
    raw = bytearray(open(p, "rb").read())
    raw[-3] ^= 0xFF  # flip a payload byte
    open(p, "wb").write(bytes(raw))
    with pytest.raises(IOError, match="CRC"):
        list(recordio.Scanner(p))


def test_empty_file_is_clean_eof(tmp_path):
    p = str(tmp_path / "e.rio")
    with recordio.Writer(p):
        pass
    assert list(recordio.Scanner(p)) == []


def test_scanner_handle_released_without_context_manager(tmp_path):
    """The ISSUE 5 satellite: iterating without `with` used to leak the
    native handle; exhaustion/error/GC now close it (weakref.finalize is
    the backstop, single-owner so no double close)."""
    import gc
    import weakref

    p = str(tmp_path / "h.rio")
    _write(p, 6)
    s = recordio.Scanner(p)
    assert list(s)  # exhaustion closes
    assert s._h is None
    s.close()  # idempotent
    # abandoned mid-iteration: GC closes via the generator finally
    s2 = recordio.Scanner(p)
    it = iter(s2)
    next(it)
    fin = s2._finalizer
    del it
    gc.collect()
    assert s2._h is None and not fin.alive
    # never iterated at all: the finalizer alone releases it
    s3 = recordio.Scanner(p)
    fin3 = s3._finalizer
    ref = weakref.ref(s3)
    del s3
    gc.collect()
    assert ref() is None and not fin3.alive


def test_zero_length_file_is_clean_eof(tmp_path):
    p = str(tmp_path / "z.rio")
    open(p, "wb").close()  # truly 0 bytes (not just a record-less file)
    assert list(recordio.Scanner(p)) == []


def test_truncated_final_chunk(tmp_path, corrupt_budget):
    p = str(tmp_path / "t.rio")
    _write(p, 12, chunk=4)  # 3 chunks of 4
    raw = open(p, "rb").read()
    open(p, "wb").write(raw[:len(raw) - 10])  # cut mid-payload of chunk 2
    # strict: loud IOError (the length pre-check catches the cut payload)
    with pytest.raises(IOError, match="truncated|exceeds file size"):
        list(recordio.read_arrays(p))
    # tolerant: chunks 0+1 survive, exactly one corrupt chunk spent
    corrupt_budget(1)
    monitor.reset()
    monitor.enable()
    try:
        s = recordio.Scanner(p)
        got = [recordio._unpack_arrays(r)[0][0] for r in s]
        assert got == list(np.arange(8, dtype="f4"))
        assert s.corrupt_chunks == 1
        assert monitor.counter("data.corrupt_chunks").value == 1
    finally:
        monitor.disable()


def test_flipped_byte_mid_chunk_crc_catch(tmp_path, corrupt_budget):
    p = str(tmp_path / "f.rio")
    _write(p, 12, chunk=4)
    raw = bytearray(open(p, "rb").read())
    # chunk frames: 20-byte header + payload; flip a byte inside chunk 1
    import struct
    (plen0,) = struct.unpack_from("<Q", raw, 8)
    off1 = 20 + plen0
    raw[off1 + 20 + 5] ^= 0xFF
    open(p, "wb").write(bytes(raw))
    corrupt_budget(1)
    monitor.reset()
    monitor.enable()
    try:
        got = [s[0][0] for s in recordio.read_arrays(p)]
        # surviving-sample parity: chunks 0 and 2 exactly
        assert got == [0, 1, 2, 3, 8, 9, 10, 11]
        assert monitor.counter("data.corrupt_chunks").value == 1
        assert monitor.counter("data.chunks_scanned").value == 3
    finally:
        monitor.disable()
    # budget exhausted: terminal classified DataError
    corrupt_budget(0)
    fluid.set_flags({"FLAGS_data_corrupt_budget": 1})
    recordio.reset_corrupt_spent()
    recordio._spend_corrupt(1, "earlier-file")  # budget already spent
    with pytest.raises(DataError, match="budget exceeded") as ei:
        list(recordio.read_arrays(p))
    assert getattr(ei.value, "budget_exhausted", False)


def test_slot_batch_reader_mixed_good_corrupt_files(tmp_path, corrupt_budget):
    good = str(tmp_path / "good.rio")
    bad = str(tmp_path / "bad.rio")
    recordio.write_arrays(
        good, [(np.full(3, i, "f4"), np.asarray([i], "i4"))
               for i in range(12)], max_chunk_records=4)
    recordio.write_arrays(
        bad, [(np.full(3, 100 + i, "f4"), np.asarray([100 + i], "i4"))
              for i in range(12)], max_chunk_records=4)
    raw = bytearray(open(bad, "rb").read())
    import struct
    (plen0,) = struct.unpack_from("<Q", raw, 8)
    raw[20 + plen0 + 20 + 3] ^= 0xFF  # corrupt chunk 1 of the bad file
    open(bad, "wb").write(bytes(raw))
    corrupt_budget(2)
    monitor.reset()
    monitor.enable()
    try:
        with recordio.SlotBatchReader([good, bad], 4, n_threads=1,
                                      drop_last=False) as r:
            ids = sorted(int(v) for b in r for v in b[1].reshape(-1))
        # parity: every sample except the bad file's chunk-1 four
        assert ids == list(range(12)) + [100, 101, 102, 103,
                                         108, 109, 110, 111]
        assert monitor.counter("data.corrupt_chunks").value == 1
    finally:
        monitor.disable()
    # strict mode keeps killing the stream
    corrupt_budget(0)
    with recordio.SlotBatchReader([good, bad], 4, n_threads=1) as r:
        with pytest.raises(RuntimeError, match="CRC"):
            list(r)


def test_corrupt_budget_not_respent_across_epochs(tmp_path, corrupt_budget):
    """Review regression: the per-run budget is a per-source high-water
    mark — a multi-epoch run re-scanning the SAME corrupt chunk every
    epoch must not re-spend it until one bad chunk kills the run."""
    import struct

    p = str(tmp_path / "ep.rio")
    _write(p, 12, chunk=4)
    raw = bytearray(open(p, "rb").read())
    (plen0,) = struct.unpack_from("<Q", raw, 8)
    raw[20 + plen0 + 20 + 5] ^= 0xFF  # corrupt chunk 1
    open(p, "wb").write(bytes(raw))
    corrupt_budget(1)
    monitor.reset()
    monitor.enable()
    try:
        r = recordio.reader_creator(p)
        for epoch in range(3):  # would die at epoch 2 under cumulative spend
            got = [s[0][0] for s in r()]
            assert got == [0, 1, 2, 3, 8, 9, 10, 11], f"epoch {epoch}"
        assert recordio.corrupt_spent() == 1
        assert monitor.counter("data.corrupt_chunks").value == 1
    finally:
        monitor.disable()


def test_queue_dataset_partial_batch_resume(tmp_path):
    """Review regression: a cursor saved after the trailing partial batch
    (drop_last=False) must not re-yield that batch on resume."""
    p = str(tmp_path / "qd.rio")
    recordio.write_arrays(
        p, [(np.full(2, i, "f4"), np.asarray([i], "i4")) for i in range(10)],
        max_chunk_records=4)

    def make():
        ds = fluid.QueueDataset()
        ds.set_batch_size(4)
        ds.set_thread(1)
        ds.set_filelist([p])
        ds.set_use_var(["a", "b"])
        ds._drop_last = False
        return ds

    ds = make()
    batches = list(ds.batches())
    assert [b["b"].shape[0] for b in batches] == [4, 4, 2]
    state = ds.state_dict()
    assert state["samples_consumed"] == 10
    ds2 = make()
    ds2.load_state_dict(state)
    assert list(ds2.batches()) == [], "resume at end must not re-yield the partial batch"


def test_scanner_safe_after_exhaustion(tmp_path):
    """Review regression: operations on an exhausted (auto-closed) scanner
    must be safe — a second pass is clean EOF, tell/seek raise a clear
    error instead of passing a NULL handle to the native layer."""
    p = str(tmp_path / "sx.rio")
    _write(p, 6)
    with recordio.Scanner(p) as s:
        assert sum(1 for _ in s) == 6
        assert sum(1 for _ in s) == 0  # second pass: clean EOF, no crash
        with pytest.raises(ValueError, match="closed"):
            s.tell()
        with pytest.raises(ValueError, match="closed"):
            s.seek(0)


def test_seek_into_corrupt_chunk_fails_not_mispositions(tmp_path, corrupt_budget):
    """Review regression: a tolerant seek whose TARGET chunk is corrupt
    must fail loudly — silently skipping it would apply the record offset
    inside the next chunk and resume the stream mispositioned."""
    import struct

    p = str(tmp_path / "sc.rio")
    _write(p, 9, chunk=3)  # 3 chunks of 3
    s = recordio.Scanner(p)
    it = iter(s)
    for _ in range(4):
        next(it)
    state = s.state_dict()  # {chunk: 1, record: 1}
    s.close()
    raw = bytearray(open(p, "rb").read())
    (plen0,) = struct.unpack_from("<Q", raw, 8)
    raw[20 + plen0 + 20 + 2] ^= 0xFF  # corrupt chunk 1 (the seek target)
    open(p, "wb").write(bytes(raw))
    corrupt_budget(4)
    s2 = recordio.Scanner(p)
    with pytest.raises(IOError, match="CRC|corrupt"):
        s2.load_state_dict(state)


def test_fault_spec_file_kinds(tmp_path, corrupt_budget):
    """corrupt_chunk@N / truncated_file@N mutate real files once, through
    the grammar + on_files hook."""
    from paddle_tpu.faults import FaultInjector, parse_fault_spec

    faults = parse_fault_spec("corrupt_chunk@1;truncated_file@2")
    assert [(f.kind, f.at) for f in faults] == [("corrupt_chunk", 1),
                                               ("truncated_file", 2)]
    p = str(tmp_path / "ff.rio")
    _write(p, 16, chunk=4)  # 4 chunks
    inj = FaultInjector("corrupt_chunk@1;truncated_file@2")
    inj.on_files([p])
    assert [f.kind for f in inj.fired()] == ["corrupt_chunk",
                                             "truncated_file"]
    inj.on_files([p])  # fires exactly once: file untouched now
    corrupt_budget(4)
    got = [s[0][0] for s in recordio.read_arrays(p)]
    # chunk 0 intact; chunk 1 CRC-dead; chunk 2 truncated => file ends
    assert got == [0, 1, 2, 3]
    assert recordio.corrupt_spent() == 2


def test_reader_creator_feeds_dataloader(tmp_path):
    """RecordIO as the file backend of the reader stack (reference
    create_recordio_file_reader op role)."""
    p = str(tmp_path / "d.rio")
    rng = np.random.RandomState(1)
    recordio.write_arrays(
        p, [(rng.rand(4).astype("f4"), np.asarray([i], "i8")) for i in range(12)])
    reader = recordio.reader_creator(p)
    got = [s[1][0] for s in reader()]
    assert got == list(range(12))
