"""Telemetry plane unit suite (ISSUE 8): flight-recorder ring + dump
semantics, heartbeat telemetry payloads, live straggler detection, the
watchdog/preemption trigger paths in-process, and the monitor-overhead
guard that keeps the always-on recorder off the dispatch hot path."""
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.dist_resilience import (CollectiveWatchdog, Heartbeat,
                                        HeartbeatConfig, _FileTransport)
from paddle_tpu.errors import CollectiveTimeoutError, PeerFailureError
from paddle_tpu.monitor import FLIGHT_RECORDER_CAP, MONITOR


@pytest.fixture(autouse=True)
def _clean_monitor():
    monitor.disable()
    monitor.reset()
    MONITOR._bb_path = None
    yield
    monitor.disable()
    monitor.reset()
    MONITOR._bb_path = None


FAST = HeartbeatConfig(interval_s=0.05, miss_factor=4, startup_grace_s=10)


# --- flight recorder ---------------------------------------------------------

def test_flight_recorder_ring_bounded_and_dump_atomic(tmp_path):
    monitor.enable()
    path = str(tmp_path / "BLACKBOX.p3.json")
    monitor.arm_flight_recorder(path, rank=3)
    for i in range(FLIGHT_RECORDER_CAP + 40):
        monitor.record_step({"t_total_s": 0.001, "i": i})
    with monitor.span("executor.execute"):
        pass
    monitor.counter("executor.recompile").inc(2)

    p = monitor.dump_blackbox("manual")
    assert p == path and os.path.exists(path)
    assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]
    doc = json.load(open(path))
    assert doc["kind"] == "blackbox" and doc["rank"] == 3
    assert doc["reason"] == "manual"
    # bounded ring keeps exactly the NEWEST records
    assert len(doc["steps"]) == FLIGHT_RECORDER_CAP
    assert doc["steps"][-1]["i"] == FLIGHT_RECORDER_CAP + 39
    assert doc["steps"][0]["i"] == 40
    assert doc["counters"]["executor.recompile"] == 2
    assert any(e["name"] == "executor.execute" for e in doc["events"])
    # step records are rank/lane-stamped for the merged post-mortem
    assert all("lane" in s for s in doc["steps"])


def test_flight_recorder_first_dump_wins(tmp_path):
    monitor.enable()
    path = str(tmp_path / "BLACKBOX.p0.json")
    monitor.arm_flight_recorder(path, rank=0)
    monitor.record_step({"t_total_s": 0.1})
    assert monitor.dump_blackbox("watchdog_timeout") == path
    # a cascading secondary failure must not overwrite the attribution
    assert monitor.dump_blackbox("crash:RuntimeError") == path
    assert json.load(open(path))["reason"] == "watchdog_timeout"
    # unarmed monitor: dump is a None no-op
    monitor.reset()
    MONITOR._bb_path = None
    assert monitor.dump_blackbox("manual") is None


def test_watchdog_expiry_triggers_dump(tmp_path):
    monitor.enable()
    path = str(tmp_path / "BLACKBOX.p0.json")
    monitor.arm_flight_recorder(path, rank=0)
    wd = CollectiveWatchdog(heartbeat=None, timeout_s=0.15, poll_s=0.02)
    with pytest.raises(CollectiveTimeoutError):
        wd.run(lambda: time.sleep(1.0), what="test.collective")
    doc = json.load(open(path))
    assert doc["reason"] == "watchdog_timeout"
    assert any(s.get("action") == "collective_timeout" for s in doc["steps"])


def test_peer_failure_triggers_dump_with_offender_telemetry(tmp_path):
    monitor.enable()
    bb = str(tmp_path / "BLACKBOX.p0.json")
    monitor.arm_flight_recorder(bb, rank=0)
    hb_dir = str(tmp_path / "hb")
    hb = Heartbeat(0, 2, config=FAST, hb_dir=hb_dir,
                   telemetry_fn=lambda: {"step": 9, "sps": 2.0})
    try:
        # peer 1 beats once with telemetry, then tombstones
        t1 = _FileTransport(hb_dir, 1, 2)
        t1.send(1, {"step": 4, "sps": 1.0, "hbm_mb": 12.5})
        hb.observe()
        t1.mark_down()
        time.sleep(FAST.interval_s / 2)  # let the poll rate-limit re-open
        wd = CollectiveWatchdog(heartbeat=hb, timeout_s=30, rank=0)
        with pytest.raises(PeerFailureError) as ei:
            wd.check_peers("allreduce")
        # the report names the offender and carries its LAST telemetry
        assert ei.value.peers == [1]
        assert "'step': 4" in str(ei.value)
        doc = json.load(open(bb))
        assert doc["reason"] == "peer_failure"
        pf = [s for s in doc["steps"] if s.get("action") == "peer_failure"]
        assert pf and pf[0]["telemetry"]["1"]["step"] == 4
    finally:
        hb.stop()


def test_sigterm_drain_triggers_dump(tmp_path):
    from paddle_tpu.faults import FaultInjector

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(x, 1), y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    feeds = [{"x": np.ones((2, 4), "f4"), "y": np.ones((2, 1), "f4")}
             for _ in range(6)]

    monitor.enable()
    path = str(tmp_path / "BLACKBOX.p0.json")
    monitor.arm_flight_recorder(path, rank=0)
    stats = fluid.resilient_train_loop(
        exe, main_p, lambda: list(feeds), [loss], scope=scope,
        injector=FaultInjector("preempt@2"),
        policy=fluid.RetryPolicy(backoff_base_s=0.0))
    assert stats.preempted
    doc = json.load(open(path))
    assert doc["reason"] == "sigterm_drain"
    assert any(s.get("kind") == "resilience_event" for s in doc["steps"])


def test_kill_worker_fault_dumps_before_sigkill(tmp_path):
    """In-process half of the kill trigger: a kill_worker entry targeting
    ANOTHER rank must not dump or kill; the gang suite
    (tests/test_gang_telemetry.py) covers the real SIGKILL path."""
    from paddle_tpu.faults import FaultInjector

    monitor.enable()
    path = str(tmp_path / "BLACKBOX.p0.json")
    monitor.arm_flight_recorder(path, rank=0)
    inj = FaultInjector("kill_worker@2:1", rank=0)  # rank 1's fault
    inj.on_dispatch(2)
    assert not os.path.exists(path)
    assert not inj.fired()


# --- heartbeat telemetry + straggler detection -------------------------------

def test_file_transport_payload_roundtrip(tmp_path):
    t0 = _FileTransport(str(tmp_path), 0, 2)
    t1 = _FileTransport(str(tmp_path), 1, 2)
    t1.send(7, {"step": 3, "sps": 1.5})
    polled = t0.poll()
    assert polled[1] == (7, {"step": 3, "sps": 1.5})
    # legacy plain-integer beat files still parse (payload None)
    with open(os.path.join(str(tmp_path), "hb-1"), "w") as f:
        f.write("9")
    assert t0.poll()[1] == (9, None)
    # tombstone wins
    t1.mark_down()
    assert t0.poll()[1] == (-1, None)


def test_local_telemetry_reads_monitor():
    from paddle_tpu.dist_resilience import local_telemetry

    monitor.enable()
    monitor.counter("executor.steps_started").inc(5)
    monitor.counter("executor.steps").inc(4)
    monitor.gauge("executor.steps_per_sec_ema").set(2.5)
    monitor.gauge("executor.last_step_s").set(0.4)
    tel = local_telemetry()
    assert tel["step"] == 5 and tel["done"] == 4
    assert tel["sps"] == 2.5 and tel["t_step_s"] == 0.4


def _mk_hb(tmp_path, my_step):
    return Heartbeat(0, 2, config=FAST, hb_dir=str(tmp_path),
                     telemetry_fn=lambda: {"step": my_step, "sps": 2.0})


def test_straggler_detection_names_lagging_rank(tmp_path):
    monitor.enable()
    hb = _mk_hb(tmp_path, my_step=10)
    try:
        t1 = _FileTransport(str(tmp_path), 1, 2)
        t1.send(1, {"step": 3, "sps": 2.0})
        hb.observe()
        # persistence: under 3 consecutive sightings nothing is reported
        hb._straggler_check()
        hb._straggler_check()
        assert monitor.counter("dist.straggler_suspects").value == 0
        hb._straggler_check()
        assert monitor.counter("dist.straggler_suspects").value == 1
        assert monitor.gauge("dist.straggler_rank").value == 1
        assert monitor.gauge("dist.step_skew_frac").value == 7.0
        evs = [r for r in monitor.step_records()
               if r.get("kind") == "dist_event"
               and r.get("action") == "straggler"]
        assert len(evs) == 1
        assert evs[0]["rank"] == 1 and evs[0]["lag_steps"] == 7.0
        assert evs[0]["telemetry"]["step"] == 3
        # one episode reports ONCE, not per beat
        hb._straggler_check()
        assert monitor.counter("dist.straggler_suspects").value == 1
        # the laggard catching back up clears the episode
        t1.send(2, {"step": 10, "sps": 2.0})
        time.sleep(FAST.interval_s / 3)
        hb.observe()
        hb._straggler_check()
        assert monitor.gauge("dist.straggler_rank").value == -1
        assert monitor.gauge("dist.step_skew_frac").value == 0.0
    finally:
        hb.stop()


def test_healthy_fast_gang_never_accumulates_straggler_sightings(tmp_path):
    """A gang stepping faster than it beats always shows SOME momentary
    lag between beat-epoch samples; because a healthy rank's reported
    step advances every beat, the (rank, step)-keyed persistence must
    never reach the reporting threshold."""
    monitor.enable()
    my_step = {"v": 10}
    hb = Heartbeat(0, 2, config=FAST, hb_dir=str(tmp_path),
                   telemetry_fn=lambda: {"step": my_step["v"], "sps": 20.0})
    try:
        t1 = _FileTransport(str(tmp_path), 1, 2)
        # rank 1 lags by 4 steps at every sample (sps * staleness), but
        # its reported step ADVANCES between beats — it is keeping up
        for k in range(8):
            t1.send(k + 1, {"step": 6 + 4 * k, "sps": 20.0})
            my_step["v"] = 10 + 4 * k
            time.sleep(FAST.interval_s / 2)
            hb.observe()
            hb._straggler_check()
        assert monitor.counter("dist.straggler_suspects").value == 0
        # a genuinely FROZEN reported step still accumulates and fires
        for _ in range(3):
            hb._straggler_check()
        assert monitor.counter("dist.straggler_suspects").value == 1
    finally:
        hb.stop()


def test_straggler_below_threshold_is_quiet(tmp_path):
    monitor.enable()
    fluid.set_flags({"FLAGS_dist_straggler_lag_steps": 5})
    try:
        hb = _mk_hb(tmp_path, my_step=10)
        try:
            t1 = _FileTransport(str(tmp_path), 1, 2)
            t1.send(1, {"step": 8, "sps": 2.0})  # lag 2 < threshold 5
            hb.observe()
            for _ in range(4):
                hb._straggler_check()
            assert monitor.counter("dist.straggler_suspects").value == 0
            assert monitor.gauge("dist.step_skew_frac").value == 2.0
        finally:
            hb.stop()
    finally:
        fluid.set_flags({"FLAGS_dist_straggler_lag_steps": 1.0})


def test_perf_report_skew_gate_counters_only(tmp_path):
    """--max-step-skew-frac must work on a gauges-only snapshot line, the
    same contract as the PR-4 dist gates."""
    import subprocess
    import sys as _sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = str(tmp_path / "m.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "snapshot", "counters": {},
                            "gauges": {"dist.step_skew_frac": 3.0}}) + "\n")
    r = subprocess.run(
        [_sys.executable, os.path.join(root, "tools", "perf_report.py"),
         "--check", path, "--max-step-skew-frac", "2"],
        capture_output=True, text=True)
    assert r.returncode == 1 and "skew fraction 3.0" in r.stdout
    r = subprocess.run(
        [_sys.executable, os.path.join(root, "tools", "perf_report.py"),
         "--check", path, "--max-step-skew-frac", "4"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout


# --- the monitor-overhead guard (tier-1 satellite) ---------------------------

def _per_call(fn, n):
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n


def test_monitor_hot_path_overhead_bounded(tmp_path):
    """The always-on flight recorder must not tax the dispatch path: a
    DISABLED monitor's span/counter entry points stay within a few
    hundred ns (branch + singleton), and an ENABLED monitor with the
    recorder armed stays within tens of µs per call.  Bounds are ~20x
    above observed cost so a loaded CI box cannot flake them, while a
    regression to per-call allocation/IO (the class of bug this guards
    against) still lands orders of magnitude above."""
    n = 20000
    monitor.disable()
    c = monitor.counter("guard.c")

    def disabled_span():
        with monitor.span("guard.s", step=1):
            pass

    assert _per_call(disabled_span, n) < 5e-6
    assert _per_call(lambda: c.inc(), n) < 2e-6
    assert _per_call(lambda: monitor.gauge("guard.g").set(1.0), n) < 5e-6

    monitor.enable()
    monitor.arm_flight_recorder(str(tmp_path / "bb.json"), 0)

    def enabled_span():
        with monitor.span("guard.s", step=1):
            pass

    assert _per_call(enabled_span, n) < 1e-4
    assert _per_call(lambda: c.inc(), n) < 5e-5
    assert _per_call(
        lambda: monitor.record_step({"kind": "pipeline_step", "x": 1}),
        2000) < 5e-4
    # the armed ring stayed bounded through all of it
    assert len(MONITOR._bb_events) <= FLIGHT_RECORDER_CAP
    assert len(MONITOR._bb_steps) <= FLIGHT_RECORDER_CAP
