"""Pallas <-> XLA parity matrix for the ISSUE-7 fused kernels
(ops/pallas_kernels.py): every registered kernel against its composite
fallback over fp32 + bf16 at per-kernel tolerances, gradients included,
plus the routing contract — `FLAGS_use_pallas` off or a platform without
Pallas support must exercise the composite path bit-for-bit.

Kernels run in interpret mode here (the tests are on the virtual CPU
mesh); the device A/B lives in tools/opbench.py --fused."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops import pallas_kernels as pk

KERNELS = pk.registered_fused_kernels()
DTYPES = ("float32", "bfloat16")


def _flat(out):
    leaves = out if isinstance(out, (list, tuple)) else [out]
    return [np.asarray(l.astype(jnp.float32)) for l in leaves]


def _max_err(got, want):
    return max((float(np.max(np.abs(g - w))) if g.size else 0.0)
               for g, w in zip(_flat(got), _flat(want)))


# --------------------------------------------------------------------------
# forward parity matrix
# --------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("kernel", KERNELS)
def test_forward_parity(kernel, dtype):
    spec = pk.FUSED_KERNELS[kernel]
    args = spec["example"](jnp.dtype(dtype))
    got = spec["fused"](args, interpret=True)
    want = spec["reference"](args)
    err = _max_err(got, want)
    assert err <= spec["tol"][dtype], (
        f"{kernel} ({dtype}): fused kernel diverged from composite, "
        f"max|d|={err:.3e} > tol={spec['tol'][dtype]:.0e}")


@pytest.mark.parametrize("kernel",
                         [k for k in KERNELS
                          if pk.FUSED_KERNELS[k]["grad_argnums"]])
def test_grad_parity_fp32(kernel):
    """Custom-VJP backward (stats recomputed flash-style) against jax.grad
    through the composite."""
    spec = pk.FUSED_KERNELS[kernel]
    args = spec["example"](jnp.float32)
    live = list(args)
    # differentiate only grad_argnums (ORIGINAL positions — e.g. the
    # softmax_xent labels are integral and excluded by the registry)
    argnums = tuple(i for i in spec["grad_argnums"] if args[i] is not None)

    def loss(fn):
        def wrapped(*a):
            out = fn(a)
            return jnp.sum(jnp.square(out.astype(jnp.float32)))
        return wrapped

    gf = jax.grad(loss(lambda a: spec["fused"](a, interpret=True)),
                  argnums=argnums)(*live)
    gr = jax.grad(loss(spec["reference"]), argnums=argnums)(*live)
    for i, (a, b) in enumerate(zip(gf, gr)):
        err = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                    - b.astype(jnp.float32))))
        # scale-aware: reduced grads (dscale/dmul sum over rows) carry
        # accumulation-order noise proportional to their magnitude
        tol = 1e-4 * (1.0 + float(jnp.max(jnp.abs(b.astype(jnp.float32)))))
        assert err <= tol, f"{kernel} d(arg{i}): max|d|={err:.3e} > {tol:.1e}"


@pytest.mark.parametrize("kernel",
                         [k for k in KERNELS
                          if pk.FUSED_KERNELS[k]["grad_argnums"]])
def test_grad_parity_multi_slab(kernel, monkeypatch):
    """Same grad parity with the VMEM budget shrunk so the row grid has
    MANY steps (grid > 1).  Pins the per-slab output contract: dm/da in the
    epilogue backward are per-row on disjoint blocks (plain store per
    step), while ln's dscale/dbias share one block across steps (genuine
    accumulation).  Interpret mode zero-fills outputs, so this can't
    reproduce an uninitialized-accumulator read — it guards the index-map
    and store/accumulate split, the device-visible half of that class."""
    monkeypatch.setattr(pk, "_VMEM_BUDGET", 64 * 1024)
    spec = pk.FUSED_KERNELS[kernel]
    args = spec["example"](jnp.float32)
    live = list(args)
    argnums = tuple(i for i in spec["grad_argnums"] if args[i] is not None)

    def loss(fn):
        def wrapped(*a):
            out = fn(a)
            return jnp.sum(jnp.square(out.astype(jnp.float32)))
        return wrapped

    gf = jax.grad(loss(lambda a: spec["fused"](a, interpret=True)),
                  argnums=argnums)(*live)
    gr = jax.grad(loss(spec["reference"]), argnums=argnums)(*live)
    for i, (a, b) in enumerate(zip(gf, gr)):
        err = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                    - b.astype(jnp.float32))))
        tol = 1e-4 * (1.0 + float(jnp.max(jnp.abs(b.astype(jnp.float32)))))
        assert err <= tol, f"{kernel} d(arg{i}): max|d|={err:.3e} > {tol:.1e}"


def test_ln_without_residual():
    """res=None is the plain-LN shape the composite lowering also hits."""
    x, _, scale, bias = pk.FUSED_KERNELS["ln_residual"]["example"](jnp.float32)
    got = pk.fused_ln_residual(x, None, scale, bias, 1e-5, True)
    want = pk._ln_reference(x, None, scale, bias)
    assert _max_err(got, want) <= 2e-5


def test_adam_shape_contract():
    """Non-lane-multiple element counts must fall back (no padding): the
    lowering guards on adam_shape_ok before routing."""
    assert pk.adam_shape_ok((512, 256))
    assert pk.adam_shape_ok((pk._ADAM_LANE,))
    assert not pk.adam_shape_ok((3, 5))
    assert not pk.adam_shape_ok(())


def test_adam_matches_composite_sequence():
    """Two chained fused steps track the composite recurrence (m/v carry)."""
    p, g, m, v = pk.FUSED_KERNELS["adam_slab"]["example"](jnp.float32)
    p1, m1, v1 = pk.fused_adam(p, g, m, v, 1e-3, 0.9, 0.999, 1e-8,
                               interpret=True)
    p2, m2, v2 = pk.fused_adam(p1, g, m1, v1, 1e-3, 0.9, 0.999, 1e-8,
                               interpret=True)
    rp, rm, rv = pk._adam_reference(p, g, m, v)
    rp2, rm2, rv2 = pk._adam_reference(rp, g, rm, rv)
    assert _max_err((p2, m2, v2), (rp2, rm2, rv2)) <= 1e-5


# --------------------------------------------------------------------------
# routing: flag off / unsupported platform -> the composite, bit-for-bit
# --------------------------------------------------------------------------


def test_use_pallas_requires_tpu_platform():
    import paddle_tpu as fluid

    class Ctx:
        platform = "cpu"

    class TpuCtx:
        platform = "tpu"

    fluid.set_flags({"FLAGS_use_pallas": True})
    try:
        assert not pk.use_pallas(Ctx())          # capability gate
        assert pk.use_pallas(TpuCtx())
    finally:
        fluid.set_flags({"FLAGS_use_pallas": False})
    assert not pk.use_pallas(TpuCtx())           # opt-in gate


def _ln_program():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = main.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8, 64], dtype="float32")
        y = fluid.layers.layer_norm(x, begin_norm_axis=2)
        h = fluid.layers.batch_norm(
            fluid.layers.conv2d(
                fluid.layers.reshape(y, [-1, 4, 16, 8]), 4, 3, padding=1))
        out = fluid.layers.mean(h) + fluid.layers.mean(y)
        fluid.optimizer.Adam(1e-3).minimize(out)
    return main, startup, out


def test_fallback_exercised_when_flag_on_but_platform_unsupported():
    """On the CPU test backend the composite must run even with
    FLAGS_use_pallas=1 (pallas_supported gates on platform), producing
    bit-identical results to the flag-off run — proof the fallback path is
    the one executing."""
    import paddle_tpu as fluid

    def run(flag):
        fluid.set_flags({"FLAGS_use_pallas": flag})
        try:
            main, startup, out = _ln_program()
            scope = fluid.Scope()
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup, scope=scope)
            feed = {"x": np.random.RandomState(0).rand(2, 8, 64).astype("f4")}
            (lv,) = exe.run(main, feed=feed, fetch_list=[out], scope=scope)
            return np.asarray(lv)
        finally:
            fluid.set_flags({"FLAGS_use_pallas": False})

    a, b = run(False), run(True)
    np.testing.assert_array_equal(a, b)


def test_flag_participates_in_compile_cache_key():
    """Toggling FLAGS_use_pallas must recompile (stale executables from the
    other routing would silently keep the old kernels)."""
    import paddle_tpu as fluid

    main, startup, out = _ln_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    feed = {"x": np.random.RandomState(0).rand(2, 8, 64).astype("f4")}
    exe.run(main, feed=feed, fetch_list=[out], scope=scope)
    n0 = len(exe._cache)
    fluid.set_flags({"FLAGS_use_pallas": True})
    try:
        exe.run(main, feed=feed, fetch_list=[out], scope=scope)
    finally:
        fluid.set_flags({"FLAGS_use_pallas": False})
    assert len(exe._cache) == n0 + 1, (
        "toggling FLAGS_use_pallas reused a cached executable")


# --------------------------------------------------------------------------
# program passes that feed the kernels
# --------------------------------------------------------------------------


def _run(prog, startup, feed, fetch, seed=5):
    import paddle_tpu as fluid

    startup.random_seed = prog.random_seed = seed
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    (out,) = exe.run(prog, feed=feed, fetch_list=[fetch], scope=scope)
    return np.asarray(out)


def test_fuse_ln_residual_pass_parity():
    import paddle_tpu as fluid
    from paddle_tpu.core.passes import apply_pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8, 32], dtype="float32")
        h = fluid.layers.scale(x, scale=0.5)
        s = fluid.layers.elementwise_add(h, x)
        y = fluid.layers.layer_norm(s, begin_norm_axis=2)
        out = fluid.layers.mean(y)
    feed = {"x": np.random.RandomState(0).rand(4, 8, 32).astype("f4")}
    base = _run(main, startup, feed, out.name)
    apply_pass(main, "fuse_ln_residual", keep=[out.name])
    ln = [op for op in main.global_block().ops if op.type == "layer_norm"][0]
    assert ln.inputs.get("Residual") == ["x"], "residual not folded in"
    assert not any(op.type == "elementwise_add"
                   for op in main.global_block().ops)
    np.testing.assert_array_equal(base, _run(main, startup, feed, out.name))


def test_fuse_ln_residual_pass_skips_multi_reader():
    """An add whose output has a second reader must NOT fuse (the other
    reader still needs the pre-norm sum)."""
    import paddle_tpu as fluid
    from paddle_tpu.core.passes import apply_pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8, 32], dtype="float32")
        s = fluid.layers.elementwise_add(fluid.layers.scale(x, scale=0.5), x)
        y = fluid.layers.layer_norm(s, begin_norm_axis=2)
        out = fluid.layers.mean(y) + fluid.layers.mean(s)  # second reader
    apply_pass(main, "fuse_ln_residual", keep=[out.name])
    ln = [op for op in main.global_block().ops if op.type == "layer_norm"][0]
    assert not ln.inputs.get("Residual")
    assert any(op.type == "elementwise_add" and "tmp" in op.output("Out")[0]
               for op in main.global_block().ops)


def test_fuse_ln_residual_pass_skips_intervening_write():
    """Fusing moves the reads of the add's inputs down to the layer_norm's
    position — an op between that mutates an input (here increment on the
    add's X) would make the fused LN observe the mutation.  Must skip."""
    import paddle_tpu as fluid
    from paddle_tpu.core.passes import apply_pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8, 32], dtype="float32")
        h = fluid.layers.scale(x, scale=0.5)
        s = fluid.layers.elementwise_add(h, x)
        fluid.layers.increment(h)  # writes h between the add and the LN
        y = fluid.layers.layer_norm(s, begin_norm_axis=2)
        out = fluid.layers.mean(y)
    apply_pass(main, "fuse_ln_residual", keep=[out.name])
    ln = [op for op in main.global_block().ops if op.type == "layer_norm"][0]
    assert not ln.inputs.get("Residual")
    assert any(op.type == "elementwise_add"
               for op in main.global_block().ops)


def test_fuse_ln_residual_pass_skips_later_writer():
    """adds keeps the LAST elementwise_add writing each Out name; when that
    add executes AFTER the layer_norm (the name is written twice), pairing
    with it would normalize the wrong sum.  Must skip."""
    import paddle_tpu as fluid
    from paddle_tpu.core.passes import apply_pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8, 32], dtype="float32")
        t = fluid.layers.elementwise_add(fluid.layers.scale(x, scale=0.5), x)
        y = fluid.layers.layer_norm(t, begin_norm_axis=2)
        out = fluid.layers.mean(y)
        t2 = fluid.layers.elementwise_add(fluid.layers.scale(x, scale=2.0), x)
    # rewrite the second add to clobber t AFTER the LN consumed it
    add2 = main.global_block().ops[-1]
    assert add2.type == "elementwise_add"
    add2.outputs["Out"] = [t.name]
    apply_pass(main, "fuse_ln_residual", keep=[out.name])
    ln = [op for op in main.global_block().ops if op.type == "layer_norm"][0]
    assert not ln.inputs.get("Residual")
    assert sum(op.type == "elementwise_add"
               for op in main.global_block().ops) == 2


def test_fuse_bn_relu_pass_skips_later_writer():
    """by_out keeps the LAST batch_norm writing each Y name; when that BN
    executes AFTER the relu (the name is written twice), fusing would pair
    a backwards def-use and miscompile.  Must skip."""
    import paddle_tpu as fluid
    from paddle_tpu.core.passes import apply_pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [4, 8, 8], dtype="float32")
        c = fluid.layers.conv2d(img, 8, 3, padding=1)
        b1 = fluid.layers.batch_norm(c)
        r = fluid.layers.relu(b1)
        out = fluid.layers.mean(r)
        fluid.layers.batch_norm(r)
    # rewrite the second BN to clobber b1's Y AFTER the relu consumed it
    bn2 = [op for op in main.global_block().ops
           if op.type == "batch_norm"][-1]
    bn2.outputs["Y"] = [b1.name]
    apply_pass(main, "fuse_bn_relu", keep=[out.name])
    assert any(op.type == "relu" for op in main.global_block().ops)
    assert not any(op.attrs.get("fuse_relu")
                   for op in main.global_block().ops
                   if op.type == "batch_norm")


def test_fuse_bn_relu_pass_parity():
    import paddle_tpu as fluid
    from paddle_tpu.core.passes import apply_pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [4, 8, 8], dtype="float32")
        c = fluid.layers.conv2d(img, 8, 3, padding=1)
        r = fluid.layers.relu(fluid.layers.batch_norm(c))
        out = fluid.layers.mean(r)
    feed = {"img": np.random.RandomState(0).rand(2, 4, 8, 8).astype("f4")}
    base = _run(main, startup, feed, out.name)
    apply_pass(main, "fuse_bn_relu", keep=[out.name])
    bn = [op for op in main.global_block().ops if op.type == "batch_norm"][0]
    assert bn.attrs.get("fuse_relu") is True
    assert not any(op.type == "relu" for op in main.global_block().ops)
    np.testing.assert_array_equal(base, _run(main, startup, feed, out.name))


def test_fuse_bn_relu_pass_skips_fetched_bn_out():
    """A BN output that is itself a fetch target must stay written."""
    import paddle_tpu as fluid
    from paddle_tpu.core.passes import apply_pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [4, 8, 8], dtype="float32")
        b = fluid.layers.batch_norm(fluid.layers.conv2d(img, 8, 3, padding=1))
        fluid.layers.relu(b)
    apply_pass(main, "fuse_bn_relu", keep=[b.name])
    assert any(op.type == "relu" for op in main.global_block().ops)


def test_fuse_bn_relu_pass_skips_intervening_write():
    """An op between the BN and the relu that overwrites the BN's Y means
    the relu never saw the BN's value — fusing would resurrect it.  The
    single-reader count alone misses this (assign reads its own input, not
    Y), so the positional hazard check must catch it."""
    import paddle_tpu as fluid
    from paddle_tpu.core.passes import apply_pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [4, 8, 8], dtype="float32")
        c = fluid.layers.conv2d(img, 8, 3, padding=1)
        b = fluid.layers.batch_norm(c)
        fluid.layers.assign(fluid.layers.scale(c, scale=2.0), output=b)
        r = fluid.layers.relu(b)
        out = fluid.layers.mean(r)
    apply_pass(main, "fuse_bn_relu", keep=[out.name])
    bn = [op for op in main.global_block().ops if op.type == "batch_norm"][0]
    assert not bn.attrs.get("fuse_relu")
    assert any(op.type == "relu" for op in main.global_block().ops)


def test_fuse_bias_act_pass_parity():
    """ISSUE 17: elementwise_add -> relu folds into one add(fuse_act) op
    with identical numerics (the bias-act epilogue's graph-side half)."""
    import paddle_tpu as fluid
    from paddle_tpu.core.passes import apply_pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [32], dtype="float32")
        h = fluid.layers.scale(x, scale=0.5)
        s = fluid.layers.elementwise_add(h, x)
        r = fluid.layers.relu(s)
        out = fluid.layers.mean(r)
    feed = {"x": np.random.RandomState(0).randn(4, 32).astype("f4")}
    base = _run(main, startup, feed, out.name)
    apply_pass(main, "fuse_bias_act", keep=[out.name])
    add = [op for op in main.global_block().ops
           if op.type == "elementwise_add"][0]
    assert add.attrs.get("fuse_act") == "relu"
    assert not any(op.type == "relu" for op in main.global_block().ops)
    np.testing.assert_array_equal(base, _run(main, startup, feed, out.name))


def test_fuse_bias_act_pass_gelu_parity():
    import paddle_tpu as fluid
    from paddle_tpu.core.passes import apply_pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [32], dtype="float32")
        s = fluid.layers.elementwise_add(fluid.layers.scale(x, scale=0.5), x)
        out = fluid.layers.mean(fluid.layers.gelu(s))
    feed = {"x": np.random.RandomState(1).randn(4, 32).astype("f4")}
    base = _run(main, startup, feed, out.name)
    apply_pass(main, "fuse_bias_act", keep=[out.name])
    add = [op for op in main.global_block().ops
           if op.type == "elementwise_add"][0]
    assert add.attrs.get("fuse_act") == "gelu"
    assert not any(op.type == "gelu" for op in main.global_block().ops)
    np.testing.assert_array_equal(base, _run(main, startup, feed, out.name))


def test_fuse_bias_act_pass_skips_multi_reader():
    """An add whose output has a second reader must NOT fuse — the other
    reader still needs the pre-activation value."""
    import paddle_tpu as fluid
    from paddle_tpu.core.passes import apply_pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [32], dtype="float32")
        s = fluid.layers.elementwise_add(fluid.layers.scale(x, scale=0.5), x)
        r = fluid.layers.relu(s)
        out = fluid.layers.mean(r) + fluid.layers.mean(s)  # second reader
    apply_pass(main, "fuse_bias_act", keep=[out.name])
    assert any(op.type == "relu" for op in main.global_block().ops)
    assert not any(op.attrs.get("fuse_act")
                   for op in main.global_block().ops
                   if op.type == "elementwise_add")


def test_fuse_bias_act_pass_skips_fetched_add_out():
    """A pre-activation sum that is itself a fetch target must stay
    written."""
    import paddle_tpu as fluid
    from paddle_tpu.core.passes import apply_pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [32], dtype="float32")
        s = fluid.layers.elementwise_add(fluid.layers.scale(x, scale=0.5), x)
        fluid.layers.relu(s)
    apply_pass(main, "fuse_bias_act", keep=[s.name])
    assert any(op.type == "relu" for op in main.global_block().ops)


def test_fuse_bias_act_pass_skips_intervening_write():
    """An op between the add and the activation that overwrites the add's
    Out means the activation never saw the add's value — fusing would
    resurrect it."""
    import paddle_tpu as fluid
    from paddle_tpu.core.passes import apply_pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [32], dtype="float32")
        h = fluid.layers.scale(x, scale=0.5)
        s = fluid.layers.elementwise_add(h, x)
        fluid.layers.assign(fluid.layers.scale(x, scale=2.0), output=s)
        r = fluid.layers.relu(s)
        out = fluid.layers.mean(r)
    apply_pass(main, "fuse_bias_act", keep=[out.name])
    assert any(op.type == "relu" for op in main.global_block().ops)
    assert not any(op.attrs.get("fuse_act")
                   for op in main.global_block().ops
                   if op.type == "elementwise_add")


# --------------------------------------------------------------------------
# opbench --fused smoke (the tier-1 wiring for the ISSUE-7 CI satellite)
# --------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", DTYPES)
def test_opbench_fused_smoke(dtype):
    """Every registered fused kernel compiles through the opbench A/B
    harness and holds parity at the registry tolerance (the harness raises
    on divergence before timing)."""
    from tools.opbench import run_fused_ab

    recs = run_fused_ab(dtypes=(dtype,), interpret=True, rounds=1, iters=1)
    assert sorted(r["kernel"] for r in recs) == KERNELS
    for rec in recs:
        assert rec["pallas"]["best_ms"] > 0 and rec["xla"]["best_ms"] > 0
