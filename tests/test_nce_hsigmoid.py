"""nce + hierarchical_sigmoid goldens and convergence (reference
nce_op.h / hierarchical_sigmoid_op.h + math/matrix_bit_code.h; OpTest
models: test_nce.py, test_hsigmoid_op.py)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.program import Program, program_guard

from op_test import OpTest


def _nce_ref(x, w, b, label, negs, num_total):
    """Transcription of nce_op.h forward with uniform sampler."""
    B, num_true = label.shape
    samples = np.concatenate([label, np.tile(negs, (B, 1))], axis=1)
    cost = np.zeros((B, 1), "float64")
    for i in range(B):
        for j, t in enumerate(samples[i]):
            o = np.exp(x[i] @ w[t] + b[t])
            bb = (1.0 / num_total) * negs.size
            cost[i, 0] += -np.log(o / (o + bb)) if j < num_true else -np.log(bb / (o + bb))
    return cost.astype("float32"), samples


def test_nce_golden_custom_negs():
    rng = np.random.RandomState(11)
    B, D, C = 5, 8, 20
    x = rng.randn(B, D).astype("float32") * 0.3
    w = rng.randn(C, D).astype("float32") * 0.3
    b = rng.randn(C).astype("float32") * 0.1
    label = rng.randint(0, C, (B, 1)).astype("int64")
    negs = np.array([1, 4, 7, 11], "int64")
    expect, samples = _nce_ref(x, w, b, label, negs, C)

    class T(OpTest):
        def setUp(self):
            self.op_type = "nce"
            self.inputs = {"Input": x, "Label": label, "Weight": w, "Bias": b}
            self.attrs = {"num_total_classes": C, "sampler": 0,
                          "custom_neg_classes": [1, 4, 7, 11],
                          "num_neg_samples": 4}
            self.outputs = {"Cost": expect}

    T().check_output(atol=1e-4, no_check_set=["SampleLogits", "SampleLabels"])


def _simple_code(label, num_classes):
    c = label + num_classes
    length = c.bit_length() - 1
    nodes = [(c >> (j + 1)) - 1 for j in range(length)]
    bits = [(c >> j) & 1 for j in range(length)]
    return nodes, bits


def _hsigmoid_ref(x, w, b, label, num_classes):
    B = x.shape[0]
    code_length = int(num_classes - 1).bit_length()
    out = np.zeros((B, 1), "float64")
    for i in range(B):
        nodes, bits = _simple_code(int(label[i, 0]), num_classes)
        pre = np.zeros(code_length)
        for j, (node, bit) in enumerate(zip(nodes, bits)):
            pre[j] = np.clip(x[i] @ w[node] + b[node], -40, 40)
        # the reference's recorded quirk: softplus over ALL code_length
        # columns (out-of-path zeros contribute log 2)
        out[i, 0] = np.log1p(np.exp(pre)).sum() - sum(
            bit * pre[j] for j, bit in enumerate(bits))
    return out.astype("float32")


def test_hsigmoid_golden():
    rng = np.random.RandomState(12)
    B, D, C = 6, 5, 11
    x = rng.randn(B, D).astype("float32") * 0.4
    w = rng.randn(C - 1, D).astype("float32") * 0.4
    b = rng.randn(C - 1).astype("float32") * 0.1
    label = rng.randint(0, C, (B, 1)).astype("int64")
    expect = _hsigmoid_ref(x, w, b, label, C)

    class T(OpTest):
        def setUp(self):
            self.op_type = "hierarchical_sigmoid"
            self.inputs = {"X": x, "Label": label, "W": w, "Bias": b}
            self.attrs = {"num_classes": C}
            self.outputs = {"Out": expect}

    T().check_output(atol=1e-4, no_check_set=["PreOut"])


def test_hsigmoid_custom_tree_golden():
    """Custom path_table/path_code equals the SimpleCode tree when the table
    encodes the same paths."""
    rng = np.random.RandomState(13)
    B, D, C = 4, 5, 8
    x = rng.randn(B, D).astype("float32") * 0.4
    w = rng.randn(C - 1, D).astype("float32") * 0.4
    b = rng.randn(C - 1).astype("float32") * 0.1
    label = rng.randint(0, C, (B, 1)).astype("int64")
    code_length = int(C - 1).bit_length()
    table = np.full((C, code_length), -1, "int64")
    code = np.full((C, code_length), -1, "int64")
    for cls in range(C):
        nodes, bits = _simple_code(cls, C)
        table[cls, :len(nodes)] = nodes
        code[cls, :len(bits)] = bits
    expect = _hsigmoid_ref(x, w, b, label, C)

    class T(OpTest):
        def setUp(self):
            self.op_type = "hierarchical_sigmoid"
            self.inputs = {"X": x, "Label": label, "W": w, "Bias": b,
                           "PathTable": table, "PathCode": code}
            self.attrs = {"num_classes": C}
            self.outputs = {"Out": expect}

    T().check_output(atol=1e-4, no_check_set=["PreOut"])


def _word2vec_style(loss_layer):
    """Tiny skip-gram-ish model: embedding -> loss_layer(emb, ctx_word)."""
    main, startup = Program(), Program()
    startup.random_seed = 9
    V, D = 30, 16
    with program_guard(main, startup):
        wrd = layers.data("w", [1], dtype="int64")
        ctx = layers.data("c", [1], dtype="int64")
        emb = layers.embedding(wrd, size=[V, D])
        emb = layers.reshape(emb, [-1, D])
        loss = layers.mean(loss_layer(emb, ctx, V))
        fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    # deterministic co-occurrence: context = (word + 1) % V
    wv = rng.randint(0, 30, (64, 1)).astype("int64")
    cv = (wv + 1) % 30
    losses = []
    for _ in range(30):
        (lv,) = exe.run(main, feed={"w": wv, "c": cv}, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, f"{losses[0]} -> {losses[-1]}"


def test_nce_word2vec_converges():
    _word2vec_style(lambda emb, ctx, V: layers.nce(
        emb, ctx, num_total_classes=V, num_neg_samples=5))


def test_nce_log_uniform_converges():
    _word2vec_style(lambda emb, ctx, V: layers.nce(
        emb, ctx, num_total_classes=V, num_neg_samples=5, sampler="log_uniform"))


def test_hsigmoid_word2vec_converges():
    _word2vec_style(lambda emb, ctx, V: layers.hsigmoid(
        emb, ctx, num_classes=V))
