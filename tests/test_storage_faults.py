"""Storage-fault chaos matrix (ISSUE 15): the storage layer itself —
not its contents — fails, and the run must survive per contract.

Covered here, all CPU-only/deterministic (tier-1):

  * spec grammar + classification: enospc/eio/slow_io/ro_fs parse, and
    OSErrors crossing the io.py choke point classify onto
    errors.StorageError with the transient/terminal split;
  * the io.py choke point: atomic tmp+fsync+rename discipline, the
    patchable fault hook, fallback-dir exemption;
  * CheckpointManager under fire: transient ENOSPC retries then enters
    DEGRADED MODE (save returns None, lag gauge + events loud) and
    recovers on the next period; terminal EROFS skips retries and lands
    in FLAGS_ckpt_fallback_dir; FLAGS_max_ckpt_lag_steps converts
    unbounded degradation to a terminal classified error;
  * resilient_train_loop end-to-end: an enospc save round costs NOTHING
    in training semantics — end-state params bit-identical to a clean
    run;
  * restore / scrub: an unreadable file (EIO mid-hash) walks back to the
    previous checkpoint / lands as an `unreadable_file` finding instead
    of raising out of the scan;
  * heartbeat-dir-on-full-disk: beat write failures go LOUD
    (dist.heartbeat.send_errors + heartbeat_send_failed event) and the
    beat thread survives — a full disk no longer reads as the rank dying;
  * perf_report --check --max-ckpt-lag-steps: pass, fail, and the
    zero-evidence-fails convention.
"""
import errno
import json
import os
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import io as pio
from paddle_tpu import monitor
from paddle_tpu.checkpoint_manager import CheckpointManager
from paddle_tpu.errors import (DataError, StorageError, attach_context,
                               classify)
from paddle_tpu.faults import FaultInjector, parse_fault_spec

# backoff-free policy: chaos tests must not sleep
FAST = dict(backoff_base_s=0.0)

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


@pytest.fixture
def mon():
    monitor.reset()
    monitor.enable()
    yield monitor
    monitor.disable()
    monitor.reset()


@pytest.fixture(autouse=True)
def _no_leaked_hook():
    yield
    # a test that failed mid-arm must not poison the rest of the suite
    pio.set_io_fault_hook(None)


def _build(seed=11):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, 8, act="relu")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
        fluid.optimizer.Adam(1e-2).minimize(loss)
    startup.random_seed = main.random_seed = seed
    return main, startup, loss


def _feeds(n, batch=8):
    rng = np.random.RandomState(0)
    out = []
    for _ in range(n):
        xv = rng.rand(batch, 4).astype("f4")
        out.append({"x": xv, "y": xv.sum(1, keepdims=True)})
    return out


def _scope_for(startup):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    return exe, scope


def _cm(root, main, scope, **kw):
    kw.setdefault("retry_policy", fluid.RetryPolicy(**FAST))
    return CheckpointManager(str(root), program=main, scope=scope, **kw)


# --- grammar + classification ------------------------------------------------

def test_storage_spec_grammar():
    fs = parse_fault_spec("enospc@4:1;eio@0:*man*;slow_io@2:250;ro_fs@3")
    assert [f.kind for f in fs] == ["enospc", "eio", "slow_io", "ro_fs"]
    assert fs[0].target_rank == 1 and fs[3].target_rank is None
    assert fs[1].arg == "*man*" and fs[2].slow_ms == 250.0
    for bad in ("slow_io@2", "slow_io@2:fast", "enospc@1:r0", "ro_fs@2:x"):
        with pytest.raises(ValueError):
            parse_fault_spec(bad)


def test_classify_storage_errnos():
    for eno in (errno.ENOSPC, errno.EIO, errno.EAGAIN, errno.ETIMEDOUT):
        ce = classify(OSError(eno, "boom"))
        assert isinstance(ce, StorageError) and ce.transient, (eno, ce)
        assert ce.phase == "storage"
    for eno in (errno.EROFS, errno.EACCES):
        ce = classify(OSError(eno, "boom"))
        assert isinstance(ce, StorageError) and not ce.transient, (eno, ce)
    # a random OSError is NOT a storage failure
    assert not isinstance(classify(OSError(errno.ENOENT, "x")), StorageError)


def test_classify_loader_phase_beats_bare_storage_errno():
    """An EIO raised while PRODUCING a batch is the data layer's problem
    (its corrupt budget owns it) — only the storage breadcrumb or a bare
    errno maps to StorageError."""
    e = attach_context(OSError(errno.EIO, "read failed"), phase="loader")
    assert isinstance(classify(e), DataError)
    e2 = attach_context(OSError(errno.EIO, "read failed"), phase="storage")
    assert isinstance(classify(e2), StorageError)


# --- the io.py choke point ---------------------------------------------------

def test_atomic_write_discipline(tmp_path):
    p = str(tmp_path / "f.json")
    pio.atomic_write(p, '{"a": 1}')
    assert json.load(open(p)) == {"a": 1}
    # no temp debris
    assert [n for n in os.listdir(tmp_path) if "tmp~" in n] == []
    # a hook failure leaves the OLD content intact and no debris
    pio.set_io_fault_hook(lambda op, path: (_ for _ in ()).throw(
        OSError(errno.ENOSPC, "full")))
    try:
        with pytest.raises(OSError):
            pio.atomic_write(p, '{"a": 2}')
    finally:
        pio.set_io_fault_hook(None)
    assert json.load(open(p)) == {"a": 1}
    assert [n for n in os.listdir(tmp_path) if "tmp~" in n] == []


def test_eio_one_shot_on_read_path(tmp_path, mon):
    """eio@0:GLOB fails the first matching read ONCE — the retry sees
    clean bytes (the flaky-NFS read every storage stack must survive)."""
    p = str(tmp_path / "x.txt")
    pio.atomic_write(p, "hello")
    inj = FaultInjector("eio@0:*x.txt").arm_io()
    try:
        with pytest.raises(OSError) as ei:
            pio.open_for_read(p)
        assert ei.value.errno == errno.EIO
        ce = classify(ei.value)
        assert isinstance(ce, StorageError) and ce.transient
        with pio.open_for_read(p) as f:
            assert f.read() == b"hello"
    finally:
        inj.disarm_io()
    assert monitor.counter("faults.eio").value == 1
    assert all(f.fired for f in inj.faults)


def test_slow_io_delays_once(tmp_path, mon):
    p = str(tmp_path / "y.txt")
    pio.atomic_write(p, "z")
    inj = FaultInjector("slow_io@0:30").arm_io()
    try:
        t0 = time.perf_counter()
        with pio.open_for_read(p) as f:
            f.read()
        slow = time.perf_counter() - t0
        t0 = time.perf_counter()
        with pio.open_for_read(p) as f:
            f.read()
        fast = time.perf_counter() - t0
    finally:
        inj.disarm_io()
    assert monitor.counter("faults.slow_io").value == 1
    assert slow >= 0.03 and fast < slow


# --- CheckpointManager degraded mode -----------------------------------------

def test_enospc_save_retries_then_degrades_then_recovers(tmp_path, mon):
    main, startup, _ = _build()
    _, scope = _scope_for(startup)
    cm = _cm(tmp_path, main, scope)
    inj = FaultInjector("enospc@4").arm_io()
    try:
        inj.set_step(2)
        assert cm.save(step=2) is not None
        inj.set_step(4)
        assert cm.save(step=4) is None  # degraded, NOT an exception
        assert cm.degraded and cm.ckpt_lag_steps == 2
        inj.set_step(6)
        out = cm.save(step=6)
        assert out is not None and not cm.degraded
    finally:
        inj.disarm_io()
    # exact ledger: one fault, the full retry budget, one degraded entry,
    # one recovery, and the lag gauge back at 0
    assert monitor.counter("faults.enospc").value == 1
    assert monitor.counter("resilience.ckpt_save_retries").value == \
        fluid.RetryPolicy().max_storage_retries
    assert monitor.counter("resilience.storage_degraded").value == 1
    assert monitor.counter("resilience.ckpt_recovered").value == 1
    assert monitor.gauge("resilience.ckpt_lag_steps").value == 0
    actions = [r["action"] for r in monitor.step_records()
               if r.get("kind") == "resilience_event"]
    assert actions == ["storage_degraded", "storage_recovered"]
    # the degraded round left no committed ckpt-4; restore takes 6
    assert cm.restore(scope=scope) == 6


def test_ro_fs_skips_retries_and_uses_fallback_dir(tmp_path, mon):
    main, startup, _ = _build()
    _, scope = _scope_for(startup)
    fb = str(tmp_path / "fallback")
    cm = _cm(tmp_path / "primary", main, scope, fallback_dir=fb)
    inj = FaultInjector("ro_fs@1").arm_io()
    try:
        inj.set_step(1)
        out = cm.save(step=1)
    finally:
        inj.disarm_io()
    # terminal errno: committed to the fallback store, zero retries spent
    assert out is not None and out.startswith(fb)
    assert not cm.degraded
    assert monitor.counter("resilience.ckpt_save_retries").value == 0
    assert monitor.counter("resilience.ckpt_fallback_saves").value == 1
    # restore merges both roots
    assert cm.restore(scope=scope) == 1
    assert cm.last_restored_dir.startswith(fb)


def test_max_ckpt_lag_converts_to_terminal_error(tmp_path, mon):
    main, startup, _ = _build()
    _, scope = _scope_for(startup)
    cm = _cm(tmp_path, main, scope)
    fluid.set_flags({"FLAGS_max_ckpt_lag_steps": 3})
    inj = FaultInjector("ro_fs@0").arm_io()
    try:
        inj.set_step(0)
        assert cm.save(step=0) is None  # lag 0: degraded, within bound
        inj.set_step(5)
        with pytest.raises(StorageError) as ei:
            cm.save(step=5)
        assert not ei.value.transient
        assert "FLAGS_max_ckpt_lag_steps" in str(ei.value)
    finally:
        inj.disarm_io()
        fluid.set_flags({"FLAGS_max_ckpt_lag_steps": 0})


def test_resilient_loop_survives_enospc_with_parity(tmp_path, mon):
    """The tentpole acceptance (single-process half): an ENOSPC window at
    a save boundary costs a checkpoint period, never the run — training
    continues through the degraded window, checkpointing recovers when
    the fault clears, and the end state is BIT-IDENTICAL to a fault-free
    run (storage faults drop no batches)."""
    main, startup, loss = _build()
    feeds = _feeds(12)

    def run(spec, root):
        exe, scope = _scope_for(startup)
        cm = _cm(root, main, scope, save_every_steps=3)
        stats = fluid.resilient_train_loop(
            exe, main, lambda: list(feeds), [loss], scope=scope,
            injector=FaultInjector(spec) if spec else None,
            checkpoint_manager=cm, policy=fluid.RetryPolicy(**FAST),
            max_inflight=3)
        return stats, scope, cm

    stats, scope, cm = run("enospc@6", tmp_path / "chaos")
    assert stats.steps == 12
    assert monitor.counter("resilience.storage_degraded").value == 1
    assert monitor.counter("resilience.ckpt_recovered").value == 1
    # the faulted period's checkpoint is missing, the next one committed
    assert "ckpt-0000000006" not in cm.checkpoints()
    assert "ckpt-0000000009" in cm.checkpoints()
    monitor.disable()
    _, ref_scope, _ = run(None, tmp_path / "clean")
    for n in ref_scope.local_var_names():
        np.testing.assert_array_equal(
            np.asarray(scope.find_var(n)), np.asarray(ref_scope.find_var(n)),
            err_msg=f"storage chaos diverged state var {n}")
    # the injector hook was disarmed on loop exit
    assert pio._IO_FAULT_HOOK is None


def test_reject_unsafe_covers_fallback_dir(tmp_path, mon):
    """Integrity quarantine (reject_unsafe) must reach fallback-dir
    checkpoints too: restore's merged walk reaches them, so a poisoned
    one written during a degraded window would otherwise bypass the
    quarantine entirely."""
    main, startup, _ = _build()
    _, scope = _scope_for(startup)
    fb = str(tmp_path / "fallback")
    cm = _cm(tmp_path / "primary", main, scope, fallback_dir=fb)
    cm.save(step=2)                      # primary, clean era
    inj = FaultInjector("ro_fs@4").arm_io()
    try:
        inj.set_step(4)
        out = cm.save(step=4)            # lands in the fallback store
    finally:
        inj.disarm_io()
    assert out is not None and out.startswith(fb)
    assert cm.reject_unsafe(3) >= 1      # step-4 fallback ckpt quarantined
    assert cm.restore(scope=scope) == 2  # NOT the poisoned fallback copy
    assert monitor.counter("integrity.ckpt_rejected").value >= 1


# --- restore walk-back + scrub on unreadable files ---------------------------

def test_restore_walks_back_past_unreadable_checkpoint(tmp_path, mon):
    main, startup, _ = _build()
    _, scope = _scope_for(startup)
    cm = _cm(tmp_path, main, scope)
    cm.save(step=2)
    cm.save(step=4)
    # every read of the NEWEST checkpoint's shards dies with EIO (a bad
    # sector under ckpt-4): the walk must land on ckpt-2, not raise
    bad = os.path.join(str(tmp_path), "ckpt-0000000004")

    def hook(op, path):
        if op == "read" and path.startswith(bad) and path.endswith(".npy"):
            raise OSError(errno.EIO, "bad sector", path)

    pio.set_io_fault_hook(hook)
    try:
        assert cm.restore(scope=scope) == 2
    finally:
        pio.set_io_fault_hook(None)
    assert monitor.counter("checkpoint.restore_skipped").value >= 1


def test_scrub_reports_unreadable_file_as_finding(tmp_path, mon):
    from paddle_tpu import integrity

    main, startup, _ = _build()
    _, scope = _scope_for(startup)
    cm = _cm(tmp_path, main, scope)
    out = cm.save(step=1)
    victim = sorted(n for n in os.listdir(out) if n.endswith(".npy"))[0]

    def hook(op, path):
        if path.endswith(victim):
            raise OSError(errno.EACCES, "permission denied", path)

    pio.set_io_fault_hook(hook)
    try:
        findings = integrity.scan_snapshot_dir(out)
    finally:
        pio.set_io_fault_hook(None)
    classes = {f["class"] for f in findings}
    assert "unreadable_file" in classes, findings
    # ...and the scrub CLI gates on it
    sys.path.insert(0, TOOLS)
    try:
        import scrub

        assert "unreadable_file" in scrub.ERROR_CLASSES
        pio.set_io_fault_hook(hook)
        try:
            assert scrub.main(["--check", str(out)]) == 1
        finally:
            pio.set_io_fault_hook(None)
        assert scrub.main(["--check", str(out)]) == 0
    finally:
        sys.path.remove(TOOLS)


# --- heartbeat dir on a full disk --------------------------------------------

def test_heartbeat_write_failure_is_loud_and_nonfatal(tmp_path, mon):
    """A full disk under PADDLE_HEARTBEAT_DIR used to kill the beat
    thread silently — peers then read a LIVE rank as dead and burned a
    gang restart on a disk hiccup.  Now: dist.heartbeat.send_errors +
    a heartbeat_send_failed event, the thread survives, and beats resume
    when the store clears."""
    from paddle_tpu.dist_resilience import Heartbeat, HeartbeatConfig

    hb_dir = str(tmp_path / "hb")
    cfg = HeartbeatConfig(interval_s=0.05, miss_factor=100.0)
    hb = Heartbeat(0, 2, hb_dir=hb_dir, config=cfg, telemetry_fn=dict)
    full = {"on": False}

    def hook(op, path):
        if full["on"] and f"{os.sep}hb-" in path:
            raise OSError(errno.ENOSPC, "disk full", path)

    pio.set_io_fault_hook(hook)
    try:
        hb.start()
        deadline = time.monotonic() + 5.0
        while monitor.counter("dist.heartbeat.sent").value < 2:
            assert time.monotonic() < deadline, "no clean beats"
            time.sleep(0.02)
        full["on"] = True
        while monitor.counter("dist.heartbeat.send_errors").value < 2:
            assert time.monotonic() < deadline, "write failures not counted"
            time.sleep(0.02)
        assert hb._thread.is_alive()
        full["on"] = False
        base = monitor.counter("dist.heartbeat.sent").value
        while monitor.counter("dist.heartbeat.sent").value < base + 2:
            assert time.monotonic() < deadline, "beats did not resume"
            time.sleep(0.02)
    finally:
        pio.set_io_fault_hook(None)
        hb.stop()
    events = [r["action"] for r in monitor.step_records()
              if r.get("kind") == "dist_event"]
    assert "heartbeat_send_failed" in events
    assert "heartbeat_send_recovered" in events


# --- the perf_report gate ----------------------------------------------------

def _write_metrics(path, records, counters=None, gauges=None):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
        f.write(json.dumps({"counters": counters or {},
                            "gauges": gauges or {}}) + "\n")


def test_perf_report_ckpt_lag_gate(tmp_path):
    sys.path.insert(0, TOOLS)
    try:
        import perf_report

        ok = str(tmp_path / "ok.jsonl")
        _write_metrics(ok, [
            {"kind": "resilience_event", "action": "storage_degraded",
             "lag_steps": 3, "at_step": 6},
            {"kind": "resilience_event", "action": "storage_recovered",
             "at_step": 9},
        ], counters={"checkpoint.saves": 3})
        assert perf_report.check(ok, max_ckpt_lag_steps=5) == 0
        assert perf_report.check(ok, max_ckpt_lag_steps=2) == 1
        # healthy run: gauge/counters only, lag 0
        clean = str(tmp_path / "clean.jsonl")
        _write_metrics(clean, [], counters={"checkpoint.saves": 4},
                       gauges={"resilience.ckpt_lag_steps": 0})
        assert perf_report.check(clean, max_ckpt_lag_steps=0) == 0
        # zero evidence must not gate green
        empty = str(tmp_path / "none.jsonl")
        _write_metrics(empty, [{"kind": "step", "recompiles_total": 0}])
        assert perf_report.check(empty, max_ckpt_lag_steps=0) == 1
    finally:
        sys.path.remove(TOOLS)
