"""Dygraph layer fill-in (VERDICT r3 #10): GroupNorm / SpectralNorm / NCE /
BilinearTensorProduct / Conv3D / Conv3DTranspose — forward+backward smoke and
static-vs-dygraph parity where a static op exists."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import dygraph


def test_group_norm_static_parity():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 8, 4, 4).astype("f4")

    with dygraph.guard():
        gn = dygraph.GroupNorm(8, groups=4)
        dy = gn(dygraph.to_variable(x)).numpy()

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", [8, 4, 4], dtype="float32")
        out = fluid.layers.group_norm(xv, groups=4)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (st,) = exe.run(main, feed={"x": x}, fetch_list=[out], scope=scope)
    np.testing.assert_allclose(dy, np.asarray(st), rtol=1e-4, atol=1e-5)


def test_spectral_norm_normalizes():
    rng = np.random.RandomState(1)
    w = (rng.randn(6, 10) * 3).astype("f4")
    with dygraph.guard():
        sn = dygraph.SpectralNorm([6, 10], power_iters=20)
        out = sn(dygraph.to_variable(w)).numpy()
    # spectral norm of the output ~ 1
    s = np.linalg.svd(out, compute_uv=False)[0]
    np.testing.assert_allclose(s, 1.0, rtol=5e-2)


def test_nce_trains():
    rng = np.random.RandomState(2)
    with dygraph.guard():
        nce = dygraph.NCE(num_total_classes=50, dim=8, num_neg_samples=5)
        opt = fluid.optimizer.SGD(0.1)
        x = dygraph.to_variable(rng.randn(16, 8).astype("f4"))
        lab = dygraph.to_variable(rng.randint(0, 50, (16, 1)).astype("int64"))
        losses = []
        for _ in range(30):
            cost = fluid.layers.mean(nce(x, lab))
            cost.backward()
            opt.minimize(cost, parameter_list=nce.parameters())
            nce.clear_gradients()
            losses.append(float(cost.numpy().reshape(-1)[0]))
        assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_bilinear_tensor_product_parity():
    rng = np.random.RandomState(3)
    x = rng.randn(4, 3).astype("f4")
    y = rng.randn(4, 5).astype("f4")
    with dygraph.guard():
        btp = dygraph.BilinearTensorProduct(3, 5, 7)
        out = btp(dygraph.to_variable(x), dygraph.to_variable(y))
        w = np.asarray(btp.weight.value)
        b = np.asarray(btp.bias.value)
        got = out.numpy()
    ref = np.einsum("nd,kde,ne->nk", x, w, y) + b
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_conv3d_layers_forward_backward():
    rng = np.random.RandomState(4)
    with dygraph.guard():
        c3 = dygraph.Conv3D(2, 4, 3, stride=1, padding=1)
        x = dygraph.to_variable(rng.rand(1, 2, 5, 5, 5).astype("f4"))
        y = c3(x)
        assert y.numpy().shape == (1, 4, 5, 5, 5)
        ct3 = dygraph.Conv3DTranspose(4, 2, 3, stride=2, padding=1)
        z = ct3(y)
        assert z.numpy().shape == (1, 2, 9, 9, 9)
        loss = fluid.layers.mean(z)
        loss.backward()
        assert np.isfinite(c3.parameters()[0].gradient()).all()


def test_conv3d_transpose_static_matches_dygraph():
    rng = np.random.RandomState(5)
    x = rng.rand(2, 3, 4, 4, 4).astype("f4")

    with dygraph.guard():
        ct = dygraph.Conv3DTranspose(3, 5, 3, stride=2, padding=1)
        w = np.asarray(ct.weight.value)
        dy = ct(dygraph.to_variable(x)).numpy()

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", [3, 4, 4, 4], dtype="float32")
        out = fluid.layers.conv3d_transpose(
            xv, 5, filter_size=3, stride=2, padding=1,
            param_attr=fluid.ParamAttr(name="ct_w"), bias_attr=False)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    scope.set_var("ct_w", w)
    (st,) = exe.run(main, feed={"x": x}, fetch_list=[out], scope=scope)
    np.testing.assert_allclose(dy, np.asarray(st), rtol=1e-4, atol=1e-5)
