"""Native C++ slot-batch parser (VERDICT r4 #6; reference
framework/data_feed.cc MultiSlotInMemoryDataFeed).

Measured on the DeepFM slot config (26 int64 ids + f32 label, bs4096):
Python thread pool ~29k ex/s (GIL-capped, under the device's 268k ex/s
consumption); native path 446k (1 thread) / 742k (4 threads) ex/s.
"""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import recordio


def _write_shards(tmp_path, n_shards=2, n_per=50, seed=0):
    rng = np.random.RandomState(seed)
    files, rows = [], []
    for shard in range(n_shards):
        p = str(tmp_path / f"part-{shard}.rio")
        samples = []
        for _ in range(n_per):
            ids = rng.randint(0, 1000, 26).astype("i8")
            lbl = rng.rand(1).astype("f4")
            samples.append((ids, lbl))
            rows.append((ids, lbl))
        recordio.write_arrays(p, samples)
        files.append(p)
    return files, rows


def test_slot_batch_reader_layout_and_counts(tmp_path):
    files, rows = _write_shards(tmp_path)
    r = recordio.SlotBatchReader(files, 16, n_threads=2)
    assert r.slots == [(np.dtype("int64"), (26,)), (np.dtype("float32"), (1,))]
    tot = sum(len(b[0]) for b in r)
    assert tot == (100 // 16) * 16  # drop_last


def test_native_path_yields_same_rows_as_python(tmp_path):
    files, rows = _write_shards(tmp_path)
    ds = fluid.QueueDataset()
    ds.set_batch_size(10)
    ds.set_thread(2)
    ds.set_filelist(files)
    ds.set_use_var(["ids", "lbl"])
    got = set()
    n = 0
    for b in ds.batches():
        assert b["ids"].shape == (10, 26) and b["lbl"].shape == (10, 1)
        for i in range(len(b["ids"])):
            got.add((b["ids"][i].tobytes(), b["lbl"][i].tobytes()))
            n += 1
    assert n == 100
    want = {(ids.tobytes(), lbl.tobytes()) for ids, lbl in rows}
    # multithreaded file interleave reorders rows; the SET of rows matches
    assert got == want


def test_drop_last_false_keeps_tail(tmp_path):
    files, _ = _write_shards(tmp_path, n_shards=1, n_per=25)
    ds = fluid.QueueDataset()
    ds.set_batch_size(10)
    ds.set_filelist(files)
    ds.set_use_var(["ids", "lbl"])
    ds._drop_last = False
    sizes = [len(b["ids"]) for b in ds.batches()]
    assert sorted(sizes) == [5, 10, 10]


def test_ragged_records_fall_back_to_python_path(tmp_path):
    # rows with VARYING shapes: the native reader refuses; batches() must
    # raise the shape error through the python path's np.stack instead of
    # serving corrupt data
    p = str(tmp_path / "ragged.rio")
    rng = np.random.RandomState(0)
    recordio.write_arrays(p, [
        (rng.randint(0, 10, 4).astype("i8"),),
        (rng.randint(0, 10, 7).astype("i8"),),
    ])
    r = recordio.SlotBatchReader([p], 2)
    with pytest.raises(RuntimeError, match="ragged|differs"):
        list(r)


def test_train_from_dataset_via_native_queue(tmp_path):
    # end-to-end: QueueDataset (native path) drives train_from_dataset
    rng = np.random.RandomState(0)
    w_true = rng.rand(5, 1).astype("f4")
    files = []
    for shard in range(2):
        p = str(tmp_path / f"t-{shard}.rio")
        samples = []
        for _ in range(40):
            f = rng.rand(5).astype("f4")
            samples.append((f, (f @ w_true).astype("f4")))
        recordio.write_arrays(p, samples)
        files.append(p)
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [5], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    ds = fluid.QueueDataset()
    ds.set_batch_size(8)
    ds.set_thread(2)
    ds.set_filelist(files)
    ds.set_use_var([x, y])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    logs = exe.train_from_dataset(main, ds, scope=scope, fetch_list=[loss],
                                  print_period=1)
    first = float(list(logs[0][1].values())[0][0])
    last = float(list(logs[-1][1].values())[0][0])
    assert last < first, (first, last)
