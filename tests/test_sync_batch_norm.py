"""SPMD batch_norm IS sync-BN: statistics reduce over the GLOBAL batch.

Reference makes cross-replica BN an explicit opt-in kernel
(operators/sync_batch_norm_op.cu); here GSPMD computes jnp.mean over the
batch-sharded axis as a cross-replica reduction automatically, so
data-parallel BN is synchronized by construction.  This test pins that
semantics: dp=2 on the same global batch must produce bit-close losses AND
identical moving statistics vs a single device.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _build():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 21
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4, 8, 8])
        y = fluid.layers.data("y", [1], dtype="int64")
        c = layers.conv2d(x, num_filters=8, filter_size=3, padding=1, bias_attr=False)
        bn = layers.batch_norm(c, act="relu")  # batch statistics path
        flat = layers.reshape(bn, [-1, 8 * 8 * 8])
        logits = layers.fc(flat, 4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    return main, startup, loss


def _stats_names(prog):
    return sorted(v.name for v in prog.list_vars()
                  if v.persistable and ("moving_mean" in v.name or "moving_variance" in v.name))


def _train(main, startup, loss, program, scope, steps=6):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(3)
    losses = []
    for _ in range(steps):
        xv = rng.rand(16, 4, 8, 8).astype("float32")
        yv = rng.randint(0, 4, (16, 1)).astype("int64")
        (lv,) = exe.run(program, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses


def test_spmd_bn_is_sync_bn():
    import jax

    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"

    main1, startup1, loss1 = _build()
    s1 = fluid.Scope()
    ref = _train(main1, startup1, loss1, main1, s1)

    main2, startup2, loss2 = _build()
    s2 = fluid.Scope()
    compiled = fluid.CompiledProgram(main2).with_data_parallel(loss_name=loss2.name)
    got = _train(main2, startup2, loss2, compiled, s2)

    # Same global batch => same BN statistics => same losses.  If BN stats
    # were per-replica (unsynchronized), each device would normalize with
    # half-batch statistics and the loss curves would diverge immediately.
    np.testing.assert_allclose(got, ref, rtol=5e-4, atol=5e-5)

    for n1, n2 in zip(_stats_names(main1), _stats_names(main2)):
        a = np.asarray(s1.find_var(n1))
        b = np.asarray(s2.find_var(n2))
        np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-5), (n1, n2)
