"""Monitor subsystem: spans, counters/gauges, executor step breakdown,
exporters, the profiler facade, and the perf_report CLI gate."""
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.monitor import MONITOR, MonitorLogger, NULL_SPAN


@pytest.fixture(autouse=True)
def _clean_monitor():
    monitor.disable()
    monitor.reset()
    yield
    monitor.disable()
    monitor.reset()


def _model():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, 16, act="relu")
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


FEED = {"x": np.ones((4, 8), "f4"), "y": np.ones((4, 1), "f4")}


# --- core: spans ------------------------------------------------------------

def test_span_nesting_and_aggregates():
    monitor.enable()
    with monitor.span("outer"):
        with monitor.span("inner", tag="a"):
            pass
        with monitor.span("inner", tag="b"):
            pass
    stats = MONITOR.span_stats()
    assert stats["outer"]["calls"] == 1
    assert stats["inner"]["calls"] == 2
    assert stats["outer"]["total_s"] >= stats["inner"]["total_s"]
    # nesting depth landed in the event buffer (inner below outer)
    depths = {name: depth for name, _, _, _, depth, _ in MONITOR.events()}
    assert depths["outer"] == 0 and depths["inner"] == 1


def test_disabled_mode_is_allocation_free():
    assert not monitor.is_enabled()
    # span() returns the one shared null singleton: nothing allocated
    assert monitor.span("a") is NULL_SPAN
    assert monitor.span("a") is monitor.span("b")
    with monitor.span("x", program="p"):
        pass
    monitor.counter("c").inc(5)
    monitor.gauge("g").set(3.0)
    assert MONITOR.span_stats() == {}
    assert MONITOR.events() == []
    assert monitor.counter("c").value == 0
    assert monitor.gauge("g").value == 0.0


def test_spans_threadsafe():
    monitor.enable()

    def work():
        for _ in range(50):
            with monitor.span("t"):
                monitor.counter("n").inc()

    ts = [threading.Thread(target=work) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert MONITOR.span_stats()["t"]["calls"] == 200
    assert monitor.counter("n").value == 200


# --- exporters: round trips -------------------------------------------------

def test_prometheus_and_json_round_trip(tmp_path):
    monitor.enable()
    monitor.counter("executor.cache_miss").inc(3)
    monitor.gauge("reader.queue_depth").set(7)
    with monitor.span("compile", program="abcd"):
        pass
    text = monitor.export_prometheus()
    assert "# TYPE paddle_tpu_executor_cache_miss counter" in text
    assert "paddle_tpu_executor_cache_miss 3" in text
    assert "paddle_tpu_reader_queue_depth 7" in text
    assert "paddle_tpu_compile_seconds_count 1" in text
    # every sample line parses as "name value"
    for ln in text.splitlines():
        if ln and not ln.startswith("#"):
            name, val = ln.rsplit(" ", 1)
            assert name.startswith("paddle_tpu_")
            float(val)  # NaN parses too

    p = tmp_path / "snap.json"
    monitor.export_json(str(p))
    snap = json.load(open(p))
    assert snap["counters"]["executor.cache_miss"] == 3
    assert snap["gauges"]["reader.queue_depth"] == 7
    assert snap["spans"]["compile"]["calls"] == 1
    assert "memory.live_array_bytes" in snap["gauges"]


def test_prometheus_hostile_names_golden():
    """Exporter hardening (ISSUE 8): hostile metric names sanitize to the
    exposition grammar, label values escape, TYPE lines never repeat, and
    sanitization collisions disambiguate with a raw= label instead of
    emitting an invalid duplicate series."""
    import re

    monitor.enable()
    monitor.counter("analysis.verify").inc(4)
    monitor.counter('hostile "name"\n{x}').inc(1)
    monitor.counter("a.b").inc(2)
    monitor.counter("a_b").inc(3)          # collides with a.b post-sanitize
    monitor.gauge("0starts.with digit").set(1.5)
    with monitor.span('span "quoted"\nname'):
        pass
    text = monitor.export_prometheus(
        labels={"rank": 0, 'bad"key': 'v"\n\\', "0zone": "a"})

    name_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    seen_types = set()
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# TYPE "):
            fam = ln.split()[2]
            assert name_re.match(fam), ln
            assert fam not in seen_types, f"duplicate TYPE: {ln}"
            seen_types.add(fam)
            continue
        # every sample: name{labels} value, name legal, labels escaped,
        # label KEYS legal too (leading digit gets a _ prefix)
        name = ln.split("{")[0].split(" ")[0]
        assert name_re.match(name), ln
        assert "\n" not in ln
        if "{" in ln:
            for kv in ln[ln.index("{") + 1:ln.rindex("}")].split('",'):
                key = kv.split("=")[0]
                assert re.match(r"^[a-zA-Z_][a-zA-Z0-9_]*$", key), ln
        val = ln.rsplit(" ", 1)[1]
        float(val)  # parses (NaN included)
    assert "paddle_tpu_analysis_verify" in text
    assert "paddle_tpu_hostile__name___x_" in text
    assert "paddle_tpu_0starts_with_digit" in text  # prefix keeps it legal
    # escaped label values: backslash, quote, newline per the format
    assert 'bad_key="v\\"\\n\\\\"' in text
    # digit-leading label key gets a _ prefix (no PROM_PREFIX on labels)
    assert '_0zone="a"' in text and "{0zone" not in text
    # collision: one family, second series disambiguated by raw label
    assert text.count("# TYPE paddle_tpu_a_b counter") == 1
    assert ('paddle_tpu_a_b{_0zone="a",bad_key="v\\"\\n\\\\",rank="0"} 2'
            in text)
    assert ',raw="a_b"} 3' in text
    # hostile span name: the summary family is sanitized too
    assert "# TYPE paddle_tpu_span__quoted__name_seconds summary" in text


def test_monitor_logger_jsonl(tmp_path):
    monitor.enable()
    path = str(tmp_path / "metrics.jsonl")
    lg = monitor.attach_logger(MonitorLogger(path))
    try:
        MONITOR.record_step({"t_total_s": 0.1})
        MONITOR.record_step({"t_total_s": 0.2})
        lg.write_snapshot()
    finally:
        monitor.detach_logger(lg)
    lines = [json.loads(ln) for ln in open(path)]
    kinds = [ln["kind"] for ln in lines]
    assert kinds == ["step", "step", "snapshot"]
    assert lines[1]["step"] == 1


# --- the executor step breakdown (ISSUE acceptance criterion) ---------------

def test_executor_step_breakdown_and_disabled_fast_path():
    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)

    monitor.enable()
    exe.run(main, feed=FEED, fetch_list=[loss], scope=scope)
    recs = monitor.step_records()
    # startup run happened before enable(): exactly one record, a cold one
    [rec] = [r for r in recs if r["program"] == main._uuid[:8]]
    assert rec["cache_hit"] is False and rec["recompiled"] is True
    # distinct per-phase timings, all really measured
    assert rec["t_lower_s"] > 0 and rec["t_compile_s"] > 0
    assert rec["t_execute_s"] > 0 and rec["t_fetch_s"] >= 0
    assert rec["t_total_s"] >= rec["t_execute_s"]
    # cache-hit + recompile counters present and coherent
    assert rec["cache_misses_total"] == 1
    assert rec["recompiles_total"] == 1
    # the phases also landed as named spans with per-program attribution
    stats = MONITOR.span_stats()
    for name in ("executor.lower", "executor.compile", "executor.execute",
                 "executor.fetch", "executor.build"):
        assert stats[name]["calls"] >= 1, name
    # per-op lower counts from core/lowering.py (trace-time census)
    assert monitor.counter("lowering.op.mul").value > 0
    assert monitor.counter("lowering.ops_total").value > 0

    # warm second run: cache hit, no recompile, still a full record
    exe.run(main, feed=FEED, fetch_list=[loss], scope=scope)
    rec2 = monitor.step_records()[-1]
    assert rec2["cache_hit"] is True and rec2["recompiled"] is False
    assert rec2["t_lower_s"] == 0.0 and rec2["t_compile_s"] == 0.0
    assert rec2["recompiles_total"] == 1  # flat — steady state

    # disabled: the fast path records nothing and allocates no spans
    monitor.disable()
    n_events = len(MONITOR.events())
    n_steps = len(monitor.step_records())
    assert monitor.span("executor.run") is NULL_SPAN
    exe.run(main, feed=FEED, fetch_list=[loss], scope=scope)
    assert len(MONITOR.events()) == n_events
    assert len(monitor.step_records()) == n_steps


def test_recompile_counter_fires_on_shape_change():
    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    monitor.enable()
    exe.run(main, feed=FEED, fetch_list=[loss], scope=scope)
    base = monitor.counter("executor.recompile").value
    # new batch size -> new executor cache entry -> fresh XLA compile
    feed2 = {"x": np.ones((8, 8), "f4"), "y": np.ones((8, 1), "f4")}
    exe.run(main, feed=feed2, fetch_list=[loss], scope=scope)
    assert monitor.counter("executor.recompile").value == base + 1
    rec = monitor.step_records()[-1]
    assert rec["cache_hit"] is False and rec["recompiled"] is True


# --- facade + trace export --------------------------------------------------

def test_chrome_trace_via_facade(tmp_path):
    from paddle_tpu import profiler

    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    profiler.start_profiler()
    exe.run(main, feed=FEED, fetch_list=[loss], scope=scope)
    profiler.stop_profiler(profile_path=str(tmp_path / "tbl.txt"))
    trace = str(tmp_path / "trace.json")
    n = profiler.export_chrome_trace(trace)
    assert n > 0
    doc = json.load(open(trace))
    names = {e["name"] for e in doc["traceEvents"]}
    assert "executor.execute" in names
    assert any(name.startswith("executor.run[") for name in names)
    # valid trace JSON: X events carry ts+dur, metadata row present
    assert all("ts" in e and "dur" in e
               for e in doc["traceEvents"] if e.get("ph") == "X")
    assert any(e.get("ph") == "M" for e in doc["traceEvents"])
    # table written by stop_profiler
    assert "executor.run" in open(tmp_path / "tbl.txt").read()


def test_reader_metrics():
    monitor.enable()
    x = fluid.layers.data("x", [4], dtype="float32")
    loader = fluid.DataLoader([x], capacity=2)
    loader.set_batch_generator(
        lambda: iter([{"x": np.ones((2, 4), "f4")} for _ in range(3)]))
    batches = list(loader)
    assert len(batches) == 3
    assert monitor.counter("reader.batches").value == 3
    assert monitor.counter("reader.bytes_staged").value == 3 * 2 * 4 * 4
    # 3 batch waits + the END-sentinel wait
    assert MONITOR.span_stats()["reader.wait"]["calls"] == 4


# --- perf_report CLI --------------------------------------------------------

def _run_perf_report(*args):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return subprocess.run(
        [sys.executable, os.path.join(root, "tools", "perf_report.py"), *args],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_perf_report_render_and_check(tmp_path):
    monitor.enable()
    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    path = str(tmp_path / "metrics.jsonl")
    lg = monitor.attach_logger(MonitorLogger(path))
    try:
        for _ in range(4):
            exe.run(main, feed=FEED, fetch_list=[loss], scope=scope)
    finally:
        monitor.detach_logger(lg)
    snap = str(tmp_path / "snap.json")
    monitor.export_json(snap)

    r = _run_perf_report(snap)
    assert r.returncode == 0, r.stderr
    assert "step breakdown" in r.stdout and "executor.execute" in r.stdout

    r = _run_perf_report("--diff", snap, snap)
    assert r.returncode == 0, r.stderr

    # healthy steady state: recompile count flat
    r = _run_perf_report("--check", path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "flat" in r.stdout

    # corrupt the steady state: a rising recompile count must fail the gate
    with open(path, "a") as f:
        f.write(json.dumps({"kind": "step", "recompiles_total": 99}) + "\n")
    r = _run_perf_report("--check", path)
    assert r.returncode == 1
    assert "recompile count moved" in r.stdout

    r = _run_perf_report("--check", str(tmp_path / "missing.jsonl"))
    assert r.returncode == 1
