"""Checkpoint save/load, inference model, LR schedulers, grad clipping
(reference: test_dist_save_load.py checkpoint equivalence;
test_learning_rate_scheduler.py; test_gradient_clip.py)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def _toy_model():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    return main, startup, pred, loss


def test_save_load_persistables_roundtrip(tmp_path):
    main, startup, pred, loss = _toy_model()
    with fluid.program_guard(main, startup):
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    for _ in range(5):
        xv = rng.rand(8, 4).astype("f4")
        exe.run(main, feed={"x": xv, "y": xv.sum(1, keepdims=True)}, fetch_list=[loss], scope=scope)
    ckpt = str(tmp_path / "ckpt")
    fluid.io.save_persistables(exe, ckpt, main, scope=scope)

    # fresh scope: load and continue — step must be bit-comparable
    scope2 = fluid.Scope()
    fluid.io.load_persistables(exe, ckpt, main, scope=scope2)
    xv = rng.rand(8, 4).astype("f4")
    feed = {"x": xv, "y": xv.sum(1, keepdims=True)}
    (a,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    (b,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope2)
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_save_load_inference_model(tmp_path):
    main, startup, pred, loss = _toy_model()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    d = str(tmp_path / "model")
    fluid.io.save_inference_model(d, ["x"], [pred], exe, main, scope=scope)

    scope2 = fluid.Scope()
    prog, feed_names, fetch_names = fluid.io.load_inference_model(d, exe, scope=scope2)
    assert feed_names == ["x"]
    xv = np.random.rand(2, 4).astype("f4")
    (a,) = exe.run(main, feed={"x": xv}, fetch_list=[pred], scope=scope)
    (b,) = exe.run(prog, feed={"x": xv}, fetch_list=fetch_names, scope=scope2)
    np.testing.assert_allclose(a, b, rtol=1e-6)
    # inference program must not require labels
    types = [op.type for op in prog.global_block().ops]
    assert "square_error_cost" not in types


@pytest.mark.parametrize(
    "make_lr,expect",
    [
        (lambda: fluid.layers.exponential_decay(0.1, 10, 0.5), lambda s: 0.1 * 0.5 ** (s / 10)),
        (lambda: fluid.layers.natural_exp_decay(0.1, 10, 0.5), lambda s: 0.1 * np.exp(-0.5 * s / 10)),
        (lambda: fluid.layers.inverse_time_decay(0.1, 10, 0.5), lambda s: 0.1 / (1 + 0.5 * s / 10)),
        (lambda: fluid.layers.polynomial_decay(0.1, 100, 0.01, 1.0), lambda s: 0.01 + (0.1 - 0.01) * (1 - s / 100)),
        (lambda: fluid.layers.cosine_decay(0.1, 1, 100), lambda s: 0.1 * 0.5 * (np.cos(np.floor(s) * np.pi / 100) + 1)),
    ],
)
def test_lr_schedules(make_lr, expect):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        loss = fluid.layers.mean(fluid.layers.square_error_cost(fluid.layers.fc(x, 1), y))
        lr = make_lr()
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    feed = {"x": np.ones((2, 4), "f4"), "y": np.ones((2, 1), "f4")}
    # first run computes with step 0 (reference _decay_step_counter semantics)
    for step in range(5):
        (lv,) = exe.run(main, feed=feed, fetch_list=[lr], scope=scope)
        np.testing.assert_allclose(lv[0], expect(step), rtol=1e-5, err_msg=f"step {step}")


def test_piecewise_decay():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        loss = fluid.layers.mean(fluid.layers.square_error_cost(fluid.layers.fc(x, 1), y))
        lr = fluid.layers.piecewise_decay([3, 6], [0.1, 0.05, 0.01])
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    feed = {"x": np.ones((2, 4), "f4"), "y": np.ones((2, 1), "f4")}
    got = []
    for step in range(8):
        (lv,) = exe.run(main, feed=feed, fetch_list=[lr], scope=scope)
        got.append(float(lv[0]))
    expect = [0.1, 0.1, 0.1, 0.05, 0.05, 0.05, 0.01, 0.01]
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_grad_clip_by_global_norm():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        pred = fluid.layers.fc(x, size=1, bias_attr=False,
                               param_attr=fluid.ParamAttr(initializer=fluid.initializer.Constant(0.0)))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.clip.set_gradient_clip(fluid.clip.GradientClipByGlobalNorm(0.01))
        opt = fluid.optimizer.SGD(learning_rate=1.0)
        _, pg = opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    # huge targets -> unclipped grad would be enormous; update must be <= lr*clip_norm
    xv = np.ones((4, 4), "f4")
    yv = np.full((4, 1), 1000.0, "f4")
    exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)
    w = scope.to_numpy(pg[0][0].name)
    assert np.linalg.norm(w) <= 0.0101, np.linalg.norm(w)
