"""Static analysis suite (paddle_tpu/core/analysis.py): program verifier,
build-time shape/dtype inference, pass-safety harness, hazard lints.

Acceptance contract: every diagnostic class plants the defect and asserts
the verifier names the offending op AND var; FLAGS_verify_program=full
catches a seeded pass miscompile that previously reached lowering."""
import contextlib

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core import analysis, passes, registry
from paddle_tpu.core.program import Operator, Program


def _hits(diags, code):
    return [d for d in diags if d.code == code]


@contextlib.contextmanager
def _flag(name, value):
    old = fluid.get_flags([name])[name]
    fluid.set_flags({name: value})
    try:
        yield
    finally:
        fluid.set_flags({name: old})


def _relu_chain():
    """x -> relu -> relu, programs fresh per test."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.relu(x)
        z = fluid.layers.relu(y)
    return main, startup, x, y, z


# --- structural verifier ---------------------------------------------------

def test_use_before_def_names_op_and_var():
    main, _, x, y, z = _relu_chain()
    blk = main.global_block()
    blk.ops = [blk.ops[1], blk.ops[0]]  # consumer now precedes producer
    hits = _hits(analysis.verify_program(main), "use_before_def")
    assert hits, "swapped producer/consumer must be flagged"
    d = hits[0]
    assert d.severity == "error"
    assert d.var == y.name and d.op_type == "relu" and d.op_idx == 0


def test_dangling_var_names_op_and_var():
    main, _, x, y, z = _relu_chain()
    main.global_block().ops[0].inputs["X"] = ["ghost"]
    hits = _hits(analysis.verify_program(main), "dangling_var")
    assert hits and hits[0].var == "ghost" and hits[0].op_idx == 0
    assert hits[0].severity == "error"


def test_unregistered_op_suggests_nearest_match():
    main, _, x, y, z = _relu_chain()
    blk = main.global_block()
    blk.ops.append(Operator(blk, "reluu", {"X": [y.name]}, {"Out": [z.name]}))
    hits = _hits(analysis.verify_program(main), "unregistered_op")
    assert hits and hits[0].op_type == "reluu"
    assert "relu" in hits[0].message  # difflib nearest-match suggestion


def test_get_op_def_error_has_suggestions_not_a_dump():
    with pytest.raises(NotImplementedError) as ei:
        registry.get_op_def("reluu")
    msg = str(ei.value)
    assert "did you mean" in msg and "relu" in msg
    # the old behavior dumped all ~250 registered names
    assert len(msg) < 500


def test_orphan_sub_block_attr_and_orphan_block():
    main, _, x, y, z = _relu_chain()
    blk = main.global_block()
    blk.ops[0].attrs["sub_block"] = 99  # no such block
    hits = _hits(analysis.verify_program(main), "orphan_sub_block")
    assert hits and hits[0].severity == "error" and hits[0].op_idx == 0

    # a block no op references is flagged as orphaned (warning)
    main2, _, x2, y2, z2 = _relu_chain()
    sub = main2.create_block()
    sub.ops.append(Operator(sub, "relu", {"X": [x2.name]}, {"Out": [y2.name]}))
    main2.rollback()
    hits = _hits(analysis.verify_program(main2), "orphan_sub_block")
    assert hits and hits[0].severity == "warning" and hits[0].block == sub.idx


def test_duplicate_param_write_names_param():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        fluid.layers.fc(x, 4)
    blk = main.global_block()
    w = blk.all_parameters()[0]
    blk.ops.append(Operator(blk, "assign", {"X": [x.name]}, {"Out": [w.name]}))
    blk.ops.append(Operator(blk, "assign", {"X": [x.name]}, {"Out": [w.name]}))
    hits = _hits(analysis.verify_program(main), "duplicate_param_write")
    assert hits and hits[0].var == w.name and hits[0].severity == "error"


def test_fetch_target_missing_raises_classified_at_executor():
    main, startup, x, y, z = _relu_chain()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    with pytest.raises(analysis.ProgramVerificationError,
                       match="fetch target 'nope'"):
        exe.run(main, feed={"x": np.ones((2, 4), "f4")},
                fetch_list=["nope"], scope=scope)


def test_feed_target_unknown_is_warning_not_error():
    main, _, x, y, z = _relu_chain()
    diags = analysis.verify_feed_fetch(main, feed_names=["mystery"],
                                       fetch_names=[z.name])
    hits = _hits(diags, "feed_target_unknown")
    assert hits and hits[0].severity == "warning" and hits[0].var == "mystery"


# --- shape/dtype inference -------------------------------------------------

def test_shape_mismatch_raises_at_append_op_with_provenance():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = fluid.layers.data("a", [4], dtype="float32")
        b = fluid.layers.data("b", [5], dtype="float32")
        with pytest.raises(analysis.ShapeInferenceError) as ei:
            fluid.layers.elementwise_add(a, b)
    msg = str(ei.value)
    assert "elementwise_add" in msg and "block 0" in msg
    # classified: the resilience taxonomy treats it as fatal (program bug)
    from paddle_tpu.errors import FatalError

    assert isinstance(ei.value, FatalError)


def test_matmul_contraction_mismatch_raises():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = fluid.layers.data("a", [3, 4], dtype="float32")
        b = fluid.layers.data("b", [5, 6], dtype="float32")
        with pytest.raises(analysis.ShapeInferenceError, match="contraction"):
            fluid.layers.matmul(a, b)


def test_infer_fills_undeclared_shapes_with_dynamic_unification():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")  # (-1, 4)
        out = fluid.layers.matmul(x, fluid.layers.data("w", [4, 8],
                                                       dtype="float32"))
    # layers.matmul leaves shape None; inference filled it, batch dim stays -1
    assert tuple(out.shape)[-1] == 8


def test_reshape_element_count_mismatch_raises():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32", append_batch_size=False)
        with pytest.raises(analysis.ShapeInferenceError, match="reshape"):
            fluid.layers.reshape(x, [3])


def test_verify_shapes_reports_rewritten_program_conflicts():
    main, startup, x, y, z = _relu_chain()
    # a rewrite that corrupts a declared shape (simulated pass bug)
    main.global_block().var(y.name).shape = (7, 9)
    diags = analysis.verify_shapes(main)
    assert any(d.code == "shape_dtype" for d in diags)


# --- hazard lints ----------------------------------------------------------

def test_donation_hazard_lint_names_reader_and_var():
    main = fluid.Program()
    blk = main.global_block()
    blk.create_var("state", shape=(1,), dtype="float32", persistable=True)
    out = blk.create_var("out", shape=(1,), dtype="float32")
    blk.append_op("increment", inputs={"X": ["state"]},
                  outputs={"Out": ["state"]}, attrs={"step": 1.0})
    blk.append_op("scale", inputs={"X": ["state"]}, outputs={"Out": ["out"]},
                  attrs={"scale": 2.0})
    hits = _hits(analysis.lint_donation(main), "donation_hazard")
    assert hits and hits[0].var == "state"
    assert hits[0].op_type == "scale" and hits[0].op_idx == 1


def test_recompile_hazard_lint_flags_dynamic_non_batch_dims():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fluid.layers.data("img", [-1, 3], dtype="float32")  # (-1, -1, 3)
    hits = _hits(analysis.lint_recompile(main), "recompile_hazard")
    assert hits and hits[0].var == "img" and "bucket" in hits[0].message
    # LoD carriers bucket their time dim: exempt
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        fluid.layers.data("seq", [3], dtype="float32", lod_level=1)
    assert not _hits(analysis.lint_recompile(main2), "recompile_hazard")


def _prog_with_collectives(order):
    p = Program()
    blk = p.global_block()
    for t in order:
        attrs = ({"sp_axis": "sp"} if t == "ring_attention"
                 else {"axis_name": "pp"})
        blk.ops.append(Operator(blk, t, {}, {}, attrs))
    return p


def test_collective_order_lint_cross_rank_divergence():
    p1 = _prog_with_collectives(["ring_attention", "pipeline"])
    p2 = _prog_with_collectives(["pipeline", "ring_attention"])
    diags = analysis.lint_collective_order([p1, p2])
    errs = [d for d in diags if d.severity == "error"]
    assert errs and "different static order" in errs[0].message
    # identical rank programs are clean
    assert not [d for d in analysis.lint_collective_order(
        [p1, _prog_with_collectives(["ring_attention", "pipeline"])])
        if d.severity == "error"]


def test_collective_order_lint_flags_divergent_control_flow():
    p = Program()
    sub = p.create_block()
    sub.ops.append(Operator(sub, "ring_attention", {}, {}, {"sp_axis": "sp"}))
    p.rollback()
    blk = p.global_block()
    cond = blk.create_var("cond", shape=(1,), dtype="bool")
    blk.ops.append(Operator(blk, "conditional_block",
                            {"Cond": [cond.name]}, {},
                            {"sub_block": sub.idx}))
    hits = _hits(analysis.lint_collective_order([p]), "collective_order")
    assert hits and hits[0].op_type == "ring_attention"
    assert "conditional" in hits[0].message


def test_determinism_lint_rng_without_seed():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        fluid.layers.dropout(x, 0.5)
    hits = _hits(analysis.lint_determinism(main), "nondeterministic_rng")
    assert hits and hits[0].op_type == "dropout"
    main.random_seed = 7
    assert not analysis.lint_determinism(main)


# --- pass-safety harness ---------------------------------------------------

def test_full_verify_catches_seeded_pass_miscompile():
    """A pass that deletes a live producer: with verification off the broken
    program reaches lowering (opaque KeyError deep in the interpreter);
    with FLAGS_verify_program the same bug is an immediate classified
    diagnostic naming the op and var."""

    @passes.register_pass("_test_seeded_miscompile")
    def _break(program):
        blk = program.global_block()
        del blk.ops[0]  # drop y's producer; z's op still reads y
        program._bump()

    try:
        # off: the pass applies silently and the bug surfaces only at
        # lowering, as an unclassified KeyError naming no op index
        main, startup, x, y, z = _relu_chain()
        with _flag("FLAGS_verify_program", "off"):
            passes.apply_pass(main, "_test_seeded_miscompile")
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        with _flag("FLAGS_verify_program", "off"):
            with pytest.raises(KeyError):
                exe.run(main, feed={"x": np.ones((1, 4), "f4")},
                        fetch_list=[z.name], scope=scope)

        # full: the harness catches it at pass-apply time with provenance
        main2, _, x2, y2, z2 = _relu_chain()
        with _flag("FLAGS_verify_program", "full"):
            with pytest.raises(analysis.PassVerificationError) as ei:
                passes.PassBuilder(["_test_seeded_miscompile"]).apply(main2)
        msg = str(ei.value)
        assert "_test_seeded_miscompile" in msg and y2.name in msg
        assert ei.value.diagnostics[0].code == "dangling_var"
    finally:
        passes._PASS_REGISTRY.pop("_test_seeded_miscompile", None)


def test_executor_structural_verify_catches_broken_program():
    """Default FLAGS_verify_program=structural turns a malformed program
    into a classified error at compile time instead of a JAX trace error."""
    main, startup, x, y, z = _relu_chain()
    main.global_block().ops[0].inputs["X"] = ["ghost"]
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    with pytest.raises(analysis.ProgramVerificationError, match="ghost"):
        exe.run(main, feed={"x": np.ones((1, 4), "f4")},
                fetch_list=[z.name], scope=scope)


# --- coverage proof --------------------------------------------------------

def test_model_zoo_infer_coverage_floor():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import program_lint

    named = program_lint.zoo_programs()
    cov = analysis.infer_coverage([p for _, p in named])
    assert cov["frac"] >= program_lint.COVERAGE_FLOOR, cov["missing_types"]
    # the gauge is the counter the CI gate reads (set on monitored runs)
    from paddle_tpu.monitor import MONITOR

    MONITOR.enable()
    try:
        analysis.verify_program(named[0][1], level="full")
        assert MONITOR.gauge_values()["analysis.infer_coverage_frac"] >= 0.8
    finally:
        MONITOR.disable()
        MONITOR.reset()
    # and the zoo itself is verifier-clean at full level
    for name, prog in named:
        errs = [d for d in analysis.verify_program(prog, level="full")
                if d.severity == "error"]
        assert not errs, (name, [str(d) for d in errs])
