"""Program-rewrite pass infrastructure (reference ir::Pass registry role)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core import passes


def _run(main, startup, feed, fetch):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    return exe.run(main, feed=feed, fetch_list=[fetch], scope=scope)[0]


def test_remove_identity_ops_preserves_semantics():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 1
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        a = fluid.layers.assign(x)
        b = fluid.layers.scale(a, scale=1.0, bias=0.0)   # identity
        out = fluid.layers.scale(b, scale=2.0)
    xv = np.random.RandomState(0).rand(3, 4).astype("f4")
    ref = _run(main, startup, {"x": xv}, out)
    n_before = len(main.global_block().ops)
    passes.apply_pass(main, "remove_identity_ops")
    n_after = len(main.global_block().ops)
    assert n_after < n_before
    got = _run(main, startup, {"x": xv}, out)
    np.testing.assert_allclose(got, ref)
    np.testing.assert_allclose(got, xv * 2.0)


def test_fold_scale_chains():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.scale(x, scale=2.0, bias=1.0)
        z = fluid.layers.scale(y, scale=3.0, bias=0.5)
    xv = np.random.RandomState(1).rand(2, 4).astype("f4")
    ref = _run(main, startup, {"x": xv}, z)
    passes.apply_pass(main, "fold_scale_chains")
    # the final scale now reads x directly with composed attrs; the bypassed
    # intermediate stays (executor prune drops it when dead)
    last = [op for op in main.global_block().ops if op.type == "scale"][-1]
    assert last.input_arg_names == ["in_x" if False else "x"]
    assert abs(last.attrs["scale"] - 6.0) < 1e-9 and abs(last.attrs["bias"] - 3.5) < 1e-9
    got = _run(main, startup, {"x": xv}, z)
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    np.testing.assert_allclose(got, xv * 6.0 + 3.5, rtol=1e-6)


def test_pass_builder_pipeline():
    pb = passes.PassBuilder()
    pb.append_pass("remove_identity_ops").append_pass("fold_scale_chains")
    assert pb.all_passes() == ["remove_identity_ops", "fold_scale_chains"]
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        out = fluid.layers.scale(fluid.layers.scale(fluid.layers.assign(x), 2.0), 5.0)
    pb.apply(main)
    got = _run(main, startup, {"x": np.ones((1, 4), "f4")}, out)
    np.testing.assert_allclose(got, np.full((1, 4), 10.0, "f4"))


def test_unknown_pass_raises():
    import pytest

    with pytest.raises(KeyError, match="unknown pass"):
        passes.apply_pass(fluid.Program(), "no_such_pass")



def test_remove_identity_respects_keep_and_subblocks():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        a = fluid.layers.assign(x)   # fetched: must survive
        b = fluid.layers.assign(x)   # unfetched: removable
        out = fluid.layers.scale(b, scale=2.0)
    passes.apply_pass(main, "remove_identity_ops", keep=[a.name])
    types = [op.type for op in main.global_block().ops]
    assert types.count("assign") == 1
    xv = np.ones((1, 4), "f4")
    got_a, got_out = None, None
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    got_a, got_out = exe.run(main, feed={"x": xv}, fetch_list=[a, out], scope=scope)
    np.testing.assert_allclose(got_a, xv)
    np.testing.assert_allclose(got_out, xv * 2)


def test_fold_does_not_cross_inplace_writes():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.scale(x, scale=2.0)
        # an intervening write to y's name (increment writes in place)
        inc = fluid.layers.increment(y, value=10.0, in_place=True)
        z = fluid.layers.scale(y, scale=3.0)
    xv = np.ones((1, 4), "f4")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (ref,) = exe.run(main, feed={"x": xv}, fetch_list=[z], scope=scope)
    passes.apply_pass(main, "fold_scale_chains")
    (got,) = exe.run(main, feed={"x": xv}, fetch_list=[z], scope=scope)
    np.testing.assert_allclose(got, ref)  # (2*1 + 10) * 3 = 36, not 6


def test_prune_requires_targets():
    import pytest

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        fluid.layers.scale(x, scale=2.0)
    with pytest.raises(ValueError, match="targets"):
        passes.apply_pass(main, "prune_dead_ops")


# --- pass safety (ISSUE 6): verifier-clean before/after each pass ----------

def _zoo_mains():
    from paddle_tpu.models import deepfm, resnet, transformer

    r_main, _, _, r_f = resnet.build(depth=50, class_dim=10,
                                     image_shape=(3, 32, 32))
    b_main, _, _, b_f = transformer.build_bert(vocab_size=200, seq_len=16,
                                               d_model=32, n_layers=1,
                                               n_heads=2, d_ff=64)
    d_main, _, _, d_f = deepfm.build()
    return [("resnet", r_main, r_f["loss"].name),
            ("bert", b_main, b_f["loss"].name),
            ("deepfm", d_main, d_f["loss"].name)]


def _errors(program):
    from paddle_tpu.core import analysis

    return [d for d in analysis.verify_program(program, level="full")
            if d.severity == "error"]


def test_registered_passes_keep_zoo_programs_verifier_clean():
    """Golden pass-safety matrix: every registered pass applied to every
    model-zoo program leaves it verifier-clean at level=full (the
    PassBuilder harness also checks this live via FLAGS_verify_program)."""
    for name, main, loss in _zoo_mains():
        assert not _errors(main), f"{name}: dirty before any pass"
        for pass_name in ("remove_identity_ops", "fold_scale_chains"):
            passes.apply_pass(main, pass_name)
            assert not _errors(main), f"{name}: dirty after {pass_name}"
        passes.apply_pass(main, "prune_dead_ops", targets=[loss])
        assert not _errors(main), f"{name}: dirty after prune_dead_ops"


def test_pass_builder_verifies_under_flag():
    """A pass that corrupts the program raises PassVerificationError from
    PassBuilder.apply when FLAGS_verify_program is on (default)."""
    import pytest

    from paddle_tpu.core import analysis

    @passes.register_pass("_test_clobber_input")
    def _clobber(program):
        program.global_block().ops[-1].inputs["X"] = ["never_defined"]
        program._bump()

    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", [4], dtype="float32")
            fluid.layers.scale(x, scale=2.0)
        with pytest.raises(analysis.PassVerificationError,
                           match="_test_clobber_input"):
            passes.PassBuilder(["_test_clobber_input"]).apply(main)
    finally:
        passes._PASS_REGISTRY.pop("_test_clobber_input", None)
