"""space_to_depth op (reference space_to_depth_op.h golden) and the
MLPerf-style reparametrized ResNet stem (models/resnet.py _s2d_stem):
exact equivalence to the 7x7/s2 stem under the weight embedding."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.program import Program, program_guard


def _ref_space_to_depth(x, blocksize):
    """Transcription of the reference OpTest helper
    (test_space_to_depth_op.py:24): iterate the INPUT index space, write a
    [B, C/bs^2, H*bs, W*bs] flat buffer, reinterpret as the declared shape."""
    batch, channel, height, width = x.shape
    bs = blocksize
    channel_out = channel // (bs * bs)
    out = np.zeros((batch, channel * bs * bs, height // bs, width // bs), x.dtype)
    out_1d = out.reshape(-1)
    x_1d = x.reshape(-1)
    for b in range(batch):
        for k in range(channel):
            for j in range(height):
                for i in range(width):
                    in_index = i + width * (j + height * (k + channel * b))
                    channel2 = k % channel_out
                    offset = k // channel_out
                    width2 = i * bs + offset % bs
                    height2 = j * bs + offset // bs
                    out_index = width2 + width * bs * (
                        height2 + height * bs * (channel2 + channel_out * b))
                    out_1d[out_index] = x_1d[in_index]
    return out


def test_space_to_depth_matches_reference_golden():
    rng = np.random.RandomState(0)
    x = rng.rand(3, 8, 6, 6).astype("float32")
    main, startup = Program(), Program()
    with program_guard(main, startup):
        xv = layers.data("x", [8, 6, 6])
        out = layers.space_to_depth(xv, 2)
    assert out.shape == (-1, 32, 3, 3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (got,) = exe.run(main, feed={"x": x}, fetch_list=[out], scope=scope)
    np.testing.assert_array_equal(got, _ref_space_to_depth(x, 2))


def test_space_to_depth_grad_roundtrip():
    """d(sum(w*s2d(x)))/dx is the inverse rearrangement of w."""
    rng = np.random.RandomState(1)
    x = rng.rand(2, 4, 4, 4).astype("float32")
    w = rng.rand(2, 16, 2, 2).astype("float32")
    main, startup = Program(), Program()
    with program_guard(main, startup):
        xv = layers.data("x", [4, 4, 4])
        s = layers.space_to_depth(xv, 2)
        wv = layers.assign(w)
        loss = layers.mean(layers.elementwise_mul(s, wv))
        (grad,) = fluid.backward.calc_gradient(loss, [xv])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (g,) = exe.run(main, feed={"x": x}, fetch_list=[grad], scope=scope)
    # chain rule through the pure rearrangement: grad = inverse-s2d of w/numel
    expect = np.zeros_like(x)
    wr = _ref_space_to_depth  # forward mapping x->out is a bijection
    # build index map by pushing an arange through the reference forward
    idx = np.arange(x.size, dtype=np.int64).reshape(x.shape).astype("float64")
    fwd = wr(idx, 2).reshape(-1).astype(np.int64)
    expect.reshape(-1)[fwd] = w.reshape(-1) / x.size
    np.testing.assert_allclose(g, expect, rtol=1e-6)


def _embed_stem_weights(w7):
    """w7 (64,3,7,7) -> w4 (64,12,4,4): zero-pad to 8x8 at offset (1,1),
    then w4[o, c*4+dy*2+dx, r, s] = w8[o, c, 2r+dy, 2s+dx]."""
    o, c, _, _ = w7.shape
    w8 = np.zeros((o, c, 8, 8), w7.dtype)
    w8[:, :, 1:, 1:] = w7
    w4 = np.zeros((o, c * 4, 4, 4), w7.dtype)
    for ci in range(c):
        for dy in range(2):
            for dx in range(2):
                w4[:, ci * 4 + dy * 2 + dx] = w8[:, ci, dy::2, dx::2]
    return w4


def test_s2d_stem_exactly_matches_conv7_stem():
    rng = np.random.RandomState(2)
    H = 32  # small stand-in for 224 (same divisibility structure)
    img = rng.randn(2, 3, H, H).astype("float32")
    w7 = (rng.randn(64, 3, 7, 7) * 0.05).astype("float32")

    def run(stem):
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("img", [3, H, H])
            if stem == "conv7":
                out = layers.conv2d(x, num_filters=64, filter_size=7, stride=2,
                                    padding=3, bias_attr=False)
            else:
                c, h, w = 3, H, H
                x6 = layers.reshape(x, [-1, c, h // 2, 2, w // 2, 2])
                x6 = layers.transpose(x6, [0, 1, 3, 5, 2, 4])
                s2d = layers.reshape(x6, [-1, c * 4, h // 2, w // 2])
                out = layers.conv2d(s2d, num_filters=64, filter_size=4, stride=1,
                                    padding=[2, 1, 2, 1], bias_attr=False)
            wname = next(v.name for v in main.list_vars()
                         if v.persistable and "conv2d" in v.name)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        scope.set_var(wname, w7 if stem == "conv7" else _embed_stem_weights(w7))
        (got,) = exe.run(main, feed={"img": img}, fetch_list=[out], scope=scope)
        return got

    a = run("conv7")
    b = run("s2d")
    assert a.shape == b.shape == (2, 64, H // 2, H // 2)
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_resnet_s2d_variant_trains():
    from paddle_tpu.models import resnet

    main, startup, feeds, fetches = resnet.build(
        depth=18, class_dim=10, image_shape=(3, 32, 32), learning_rate=0.05,
        stem="space_to_depth")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    startup.random_seed = 1
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    img = rng.rand(8, 3, 32, 32).astype("float32")
    lab = rng.randint(0, 10, (8, 1)).astype("int64")
    losses = []
    for _ in range(4):
        (lv,) = exe.run(main, feed={"img": img, "label": lab},
                        fetch_list=[fetches["loss"]], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
