"""Backward-overlapped dp gradient all-reduce (ISSUE 7):
`parallel.distributed.make_grad_sync` bucketing + the
`CompiledProgram.with_grad_overlap` end-to-end path on the virtual CPU
mesh.  The real 2-process A/B lives in `bench.py --overlap`
(tests/dist_worker_overlap.py); the micro A/B in
tools/collective_bench.py --overlap."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu.core.jax_compat import shard_map
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.distributed import make_grad_sync, plan_buckets


# --------------------------------------------------------------------------
# bucket planning
# --------------------------------------------------------------------------


def test_plan_buckets_caps_and_preserves_order():
    sizes = [("a", 3), ("b", 3), ("c", 3), ("d", 3)]
    assert plan_buckets(sizes, 6) == [["a", "b"], ["c", "d"]]
    assert plan_buckets(sizes, 7) == [["a", "b"], ["c", "d"]]
    assert plan_buckets(sizes, 100) == [["a", "b", "c", "d"]]
    assert plan_buckets(sizes, 1) == [["a"], ["b"], ["c"], ["d"]]


def test_plan_buckets_oversize_grad_gets_own_bucket():
    assert plan_buckets([("big", 50), ("s1", 2), ("s2", 2)], 10) == \
        [["big"], ["s1", "s2"]]
    assert plan_buckets([("s1", 2), ("big", 50), ("s2", 2)], 10) == \
        [["s1"], ["big"], ["s2"]]


def test_plan_buckets_empty():
    assert plan_buckets([], 10) == []


# --------------------------------------------------------------------------
# make_grad_sync: dense mean-reduce, bucketed == serial element-wise
# --------------------------------------------------------------------------


def _sync_under_shard_map(sync, grads, mesh):
    """Run `sync` over per-worker grads inside a shard_map dp region and
    return each output stacked over workers."""
    names = [n for n, _ in grads[0]]

    def worker(*stacked):
        per = [(n, g[0]) for n, g in zip(names, stacked)]
        out = sync(per)
        return tuple(out[n][None] for n in names)

    args = [jnp.stack([dict(g)[n] for g in grads]) for n in names]
    f = shard_map(worker, mesh=mesh,
                  in_specs=tuple(P("dp") for _ in names),
                  out_specs=tuple(P("dp") for _ in names))
    return dict(zip(names, f(*args)))


@pytest.mark.parametrize("mode", ["serial", "bucketed"])
def test_grad_sync_mean_reduces(mode):
    mesh = make_mesh((4,), ("dp",))
    rng = np.random.RandomState(0)
    grads = [[("g0", jnp.asarray(rng.randn(8, 4), jnp.float32)),
              ("g1", jnp.asarray(rng.randn(16), jnp.float32))]
             for _ in range(4)]
    sync = make_grad_sync("dp", bucket_bytes=64, mode=mode)
    out = _sync_under_shard_map(sync, grads, mesh)
    for n in ("g0", "g1"):
        want = np.mean([np.asarray(dict(g)[n]) for g in grads], axis=0)
        # every worker must hold the same mean
        for w in range(4):
            np.testing.assert_allclose(np.asarray(out[n][w]), want,
                                       rtol=1e-6, atol=1e-6)


def test_grad_sync_bucketed_bitwise_matches_serial():
    """Bucketing never changes what each grad element is summed with, so
    the two modes must agree to the BIT — the property that makes the
    bench A/B isolate scheduling."""
    mesh = make_mesh((4,), ("dp",))
    rng = np.random.RandomState(1)
    grads = [[(f"g{i}", jnp.asarray(rng.randn(64), jnp.float32))
              for i in range(6)] for _ in range(4)]
    outs = {}
    for mode in ("serial", "bucketed"):
        sync = make_grad_sync("dp", bucket_bytes=64 * 4 * 2, mode=mode)
        outs[mode] = _sync_under_shard_map(sync, grads, mesh)
    for n in outs["serial"]:
        np.testing.assert_array_equal(np.asarray(outs["serial"][n]),
                                      np.asarray(outs["bucketed"][n]))


def test_grad_sync_unknown_mode_raises():
    with pytest.raises(ValueError, match="unknown mode"):
        make_grad_sync("dp", 1024, mode="pipelined")


# --------------------------------------------------------------------------
# end-to-end: CompiledProgram.with_grad_overlap
# --------------------------------------------------------------------------


def _mlp(seed=11):
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [16], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, 32, act="relu")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
        fluid.optimizer.Adam(1e-2).minimize(loss)
    return main, startup, loss


def _train(mode, steps=4, n_steps=1, bucket_mb=0.001):
    main, startup, loss = _mlp()
    cp = fluid.CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    if mode:
        cp = cp.with_grad_overlap(bucket_mb=bucket_mb, mode=mode)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(steps):
        if n_steps > 1:
            feed = {"x": rng.rand(n_steps, 8, 16).astype("f4"),
                    "y": rng.rand(n_steps, 8, 1).astype("f4")}
        else:
            feed = {"x": rng.rand(8, 16).astype("f4"),
                    "y": rng.rand(8, 1).astype("f4")}
        (lv,) = exe.run(cp, feed=feed, fetch_list=[loss], scope=scope,
                        steps=n_steps)
        losses.append(np.asarray(lv).reshape(-1))
    # keyed by build order, not name: each _mlp() call advances the
    # unique_name counter, so names differ across arms
    params = [np.asarray(scope.find_var(p.name)).copy()
              for p in sorted(main.all_parameters(), key=lambda p: p.name)]
    return np.concatenate(losses), params


def test_overlap_arms_bit_identical_to_gspmd():
    """serial == bucketed == GSPMD-derived collectives, to the bit: the
    overlap path changes scheduling, never numerics."""
    losses = {}
    params = {}
    for mode in (None, "serial", "bucketed"):
        losses[mode], params[mode] = _train(mode)
    np.testing.assert_array_equal(losses["serial"], losses["bucketed"])
    np.testing.assert_array_equal(losses[None], losses["bucketed"])
    for a, b, c in zip(params[None], params["serial"], params["bucketed"]):
        np.testing.assert_array_equal(b, c)
        np.testing.assert_array_equal(a, c)


def test_overlap_composes_with_multi_step_scan():
    """steps>1 scanned dispatches run inside the manual dp region too."""
    l1, p1 = _train("bucketed", steps=2, n_steps=3)
    l2, p2 = _train("serial", steps=2, n_steps=3)
    np.testing.assert_array_equal(l1, l2)
    for a, b in zip(p1, p2):
        np.testing.assert_array_equal(a, b)


def test_overlap_syncs_bn_running_stats():
    """BN running mean/var updates are per-shard batch stats (not
    grad-derived), so the overlap worker must dp-mean them before claiming
    replication — serial and bucketed arms must agree to the bit on EVERY
    persistable, running stats included, and the stats must have moved."""
    def build(seed=13):
        main, startup = fluid.Program(), fluid.Program()
        startup.random_seed = main.random_seed = seed
        with fluid.program_guard(main, startup):
            img = fluid.layers.data("img", [3, 8, 8], dtype="float32")
            y = fluid.layers.data("y", [1], dtype="float32")
            c = fluid.layers.batch_norm(
                fluid.layers.conv2d(img, 4, 3, padding=1))
            loss = fluid.layers.mean(fluid.layers.square_error_cost(
                fluid.layers.fc(c, 1), y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        return main, startup, loss

    state = {}
    for mode in ("serial", "bucketed"):
        main, startup, loss = build()
        cp = (fluid.CompiledProgram(main)
              .with_data_parallel(loss_name=loss.name)
              .with_grad_overlap(bucket_mb=0.001, mode=mode))
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup, scope=scope)
        init = {n: np.asarray(scope.find_var(n)).copy()
                for n in scope.var_names()}
        rng = np.random.RandomState(0)
        for _ in range(3):
            feed = {"img": rng.rand(16, 3, 8, 8).astype("f4"),
                    "y": rng.rand(16, 1).astype("f4")}
            exe.run(cp, feed=feed, fetch_list=[loss], scope=scope)
        # keyed by build order (unique names differ across arms)
        state[mode] = ([init[n] for n in sorted(init)],
                       [np.asarray(scope.find_var(n)).copy()
                        for n in sorted(init)])
    for (ia, fa), (ib, fb) in [(state["serial"], state["bucketed"])]:
        for a, b in zip(fa, fb):
            np.testing.assert_array_equal(a, b)
        # the BN running stats moved off their init (the update ran)
        moved = [not np.array_equal(i, f) for i, f in zip(ia, fa)]
        assert any(moved)


def test_overlap_syncs_auc_accumulators():
    """auc's StatPos/StatNeg histograms are the OTHER non-grad-derived
    written state: additive accumulators.  Each dp shard buckets only ITS
    samples, so the overlap worker must psum the per-shard DELTA (not
    pmean, not raw psum — the replicated base would be counted n_dp
    times).  Integer histogram adds are order-invariant, so all three arms
    (GSPMD / serial / bucketed) must agree to the bit and equal the
    full-batch accumulation."""
    def build(seed=17):
        main, startup = fluid.Program(), fluid.Program()
        startup.random_seed = main.random_seed = seed
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", [16], dtype="float32")
            y = fluid.layers.data("y", [1], dtype="float32")
            yl = fluid.layers.data("yl", [1], dtype="int64")
            pred = fluid.layers.sigmoid(fluid.layers.fc(x, 1))
            fluid.layers.auc(pred, yl, num_thresholds=255)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        return main, startup, loss

    state = {}
    for mode in (None, "serial", "bucketed"):
        main, startup, loss = build()
        cp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        if mode:
            cp = cp.with_grad_overlap(bucket_mb=0.001, mode=mode)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup, scope=scope)
        stat_names = sorted(n for n in scope.var_names()
                            if ".stat_" in n)
        assert len(stat_names) == 2
        rng = np.random.RandomState(0)
        for _ in range(3):
            xv = rng.rand(16, 16).astype("f4")
            feed = {"x": xv,
                    "y": rng.rand(16, 1).astype("f4"),
                    "yl": (rng.rand(16, 1) > 0.5).astype("i8")}
            exe.run(cp, feed=feed, fetch_list=[loss], scope=scope)
        # keyed by build order (unique names differ across arms)
        state[mode] = [np.asarray(scope.find_var(n)).copy()
                       for n in stat_names]
    for arm in ("serial", "bucketed"):
        for a, b in zip(state[None], state[arm]):
            np.testing.assert_array_equal(a, b)
    # the histograms actually accumulated: 3 steps x 16 samples
    assert sum(int(s.sum()) for s in state["bucketed"]) == 3 * 16


def test_overlap_rejects_non_scalar_fetch():
    """Overlap fetches come back dp-MEANed — exact for scalar losses and
    metrics, garbage for per-sample outputs (the element-wise average of
    DIFFERENT samples at 1/n_dp the batch).  Must refuse at compile."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [16], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(1e-2).minimize(loss)
    cp = (fluid.CompiledProgram(main)
          .with_data_parallel(loss_name=loss.name)
          .with_grad_overlap(bucket_mb=1.0))
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    # batch 32 on the 8-device mesh: per-shard pred is (4, 1), so the
    # trace-time guard sees a genuinely non-scalar fetch (a per-shard
    # size-1 fetch is indistinguishable from a scalar metric and passes)
    feed = {"x": np.random.RandomState(0).rand(32, 16).astype("f4"),
            "y": np.random.RandomState(1).rand(32, 1).astype("f4")}
    with pytest.raises(ValueError, match="dp-MEAN"):
        exe.run(cp, feed=feed, fetch_list=[pred, loss], scope=scope)


def test_overlap_requires_mesh():
    main, startup, loss = _mlp()
    cp = fluid.CompiledProgram(main).with_grad_overlap(bucket_mb=1.0)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    with pytest.raises(ValueError, match="needs a mesh"):
        exe.run(cp, feed={"x": np.zeros((8, 16), "f4"),
                          "y": np.zeros((8, 1), "f4")},
                fetch_list=[loss], scope=scope)


def test_overlap_rejects_local_sgd_composition():
    main, _, loss = _mlp()
    with pytest.raises(ValueError, match="local_sgd"):
        fluid.CompiledProgram(main).with_local_sgd(2).with_grad_overlap()
    with pytest.raises(ValueError, match="local_sgd"):
        fluid.CompiledProgram(main).with_grad_overlap().with_local_sgd(2)


def test_overlap_rejects_bad_args():
    main, _, _ = _mlp()
    with pytest.raises(ValueError, match="unknown mode"):
        fluid.CompiledProgram(main).with_grad_overlap(mode="async")
    with pytest.raises(ValueError, match="must be > 0"):
        fluid.CompiledProgram(main).with_grad_overlap(bucket_mb=0.0)


def test_overlap_bucket_mb_defaults_to_flag():
    main, _, _ = _mlp()
    fluid.set_flags({"FLAGS_dp_bucket_mb": 7.5})
    try:
        cp = fluid.CompiledProgram(main).with_grad_overlap()
        assert cp.grad_overlap_bucket_mb == 7.5
    finally:
        fluid.set_flags({"FLAGS_dp_bucket_mb": 4.0})


def test_overlap_sparse_grads_match_gspmd():
    """SelectedRows (is_sparse embedding) grads ride the all-gather branch
    of make_grad_sync; losses and params must track the GSPMD arm."""

    def build():
        main, startup = fluid.Program(), fluid.Program()
        startup.random_seed = main.random_seed = 13
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data("ids", [4], dtype="int64")
            y = fluid.layers.data("y", [1], dtype="float32")
            emb = fluid.layers.embedding(ids, size=(50, 8), is_sparse=True)
            h = fluid.layers.reduce_mean(emb, dim=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        return main, startup, loss

    def run(mode):
        main, startup, loss = build()
        cp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        if mode:
            cp = cp.with_grad_overlap(bucket_mb=0.001, mode=mode)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup, scope=scope)
        rng = np.random.RandomState(0)
        out = []
        for _ in range(3):
            feed = {"ids": rng.randint(0, 50, (8, 4)).astype("i8"),
                    "y": rng.rand(8, 1).astype("f4")}
            (lv,) = exe.run(cp, feed=feed, fetch_list=[loss], scope=scope)
            out.append(float(np.asarray(lv).reshape(-1)[0]))
        emb_w = np.asarray(scope.find_var(
            [p.name for p in main.all_parameters()
             if "emb" in p.name.lower() or "embedding" in p.name][0])).copy()
        return out, emb_w

    l_g, w_g = run(None)
    l_b, w_b = run("bucketed")
    np.testing.assert_allclose(l_b, l_g, rtol=1e-6)
    np.testing.assert_allclose(w_b, w_g, rtol=1e-6, atol=1e-7)
