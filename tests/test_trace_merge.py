"""tools/trace_merge.py golden suite (ISSUE 8): synthetic 2-rank JSONL
with a planted straggler must attribute the correct rank with measured
skew; merged Chrome traces get one pid lane per rank; the CLI gates."""
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))

import trace_merge  # noqa: E402

STEP_S = 0.100     # synthetic mean step time
PLANT_SKEW = 0.040  # rank 1 arrives this late from step 5 on


def _write_rank(dirpath, rank, lag_from=None, lag_s=0.0, n=12):
    """Synthetic per-rank metrics stream: csig-stamped step records with
    ts_dispatch arrivals every STEP_S; `lag_from` plants a straggler."""
    path = os.path.join(dirpath, f"metrics.p{rank}.jsonl")
    with open(path, "w") as f:
        for k in range(n):
            ts = 100.0 + STEP_S * k
            if lag_from is not None and k >= lag_from:
                ts += lag_s
            f.write(json.dumps({
                "kind": "step", "step": k, "csig": "ab12cd34",
                "lane": rank, "ts": ts + 0.001, "ts_dispatch": ts,
                "t_total_s": STEP_S * 0.9,
            }) + "\n")
        # snapshot tail like a real MonitorLogger stream
        f.write(json.dumps({"kind": "snapshot", "counters": {},
                            "gauges": {}}) + "\n")
    return path


def _write_trace(dirpath, rank):
    path = os.path.join(dirpath, f"trace.p{rank}.json")
    with open(path, "w") as f:
        json.dump({"traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 99,
             "args": {"name": "old"}},
            {"name": "executor.execute", "ph": "X", "pid": 99, "tid": 1,
             "ts": 1.0, "dur": 2.0, "cat": "span"},
        ]}, f)
    return path


def test_planted_straggler_attributed_with_measured_skew(tmp_path):
    d = str(tmp_path)
    _write_rank(d, 0)
    _write_rank(d, 1, lag_from=5, lag_s=PLANT_SKEW)

    report = trace_merge.skew_from_dir(d)
    assert report is not None
    assert report["ranks"] == [0, 1]
    assert report["steps_correlated"] == 12
    # the planted straggler is named...
    assert report["straggler"]["rank"] == 1
    # ...with the planted skew measured (exactly, on synthetic data)
    assert report["straggler"]["mean_skew_s_when_last"] == pytest.approx(
        PLANT_SKEW, rel=1e-6)
    assert report["max_skew_s"] == pytest.approx(PLANT_SKEW, rel=1e-6)
    assert report["max_skew_frac"] == pytest.approx(
        PLANT_SKEW / STEP_S, rel=0.05)
    # per-step attribution: lagged steps name rank 1 last, by the skew
    lagged = [e for e in report["entries"] if e["step"] >= 5]
    assert lagged and all(e["last_rank"] == 1 for e in lagged)
    assert all(e["skew_s"] == pytest.approx(PLANT_SKEW, rel=1e-6)
               for e in lagged)
    # pre-plant steps carry no skew
    assert all(e["skew_s"] == pytest.approx(0.0, abs=1e-9)
               for e in report["entries"] if e["step"] < 5)


def test_no_dominant_straggler_on_balanced_arrivals(tmp_path):
    d = str(tmp_path)
    _write_rank(d, 0)
    _write_rank(d, 1)  # identical arrivals: ties, no straggler
    report = trace_merge.skew_from_dir(d)
    assert report["steps_correlated"] == 12
    assert "straggler" not in report or \
        report["last_arrival_counts"].get("1", 0) <= 12


def test_merge_traces_one_lane_per_rank(tmp_path):
    d = str(tmp_path)
    _write_trace(d, 0)
    _write_trace(d, 1)
    out = str(tmp_path / "merged.json")
    files = trace_merge.find_rank_files(d)
    n = trace_merge.merge_traces(files["traces"], out)
    assert n == 2
    doc = json.load(open(out))
    pids = {e["pid"] for e in doc["traceEvents"]}
    assert pids == {0, 1}
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M"}
    assert names == {"rank0", "rank1"}


def test_cli_check_gate_and_report(tmp_path):
    d = str(tmp_path)
    _write_rank(d, 0)
    _write_rank(d, 1, lag_from=0, lag_s=PLANT_SKEW)
    rep = str(tmp_path / "skew.json")

    def run(*extra):
        return subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "trace_merge.py"),
             d, *extra], capture_output=True, text=True)

    # skew frac = 0.4: passes a 0.5 gate, fails a 0.2 gate naming rank 1
    r = run("--report", rep, "--check", "--max-step-skew-frac", "0.5")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "STRAGGLER: rank 1" in r.stdout
    assert json.load(open(rep))["straggler"]["rank"] == 1
    r = run("--check", "--max-step-skew-frac", "0.2")
    assert r.returncode == 1
    assert "rank 1 is the straggler" in r.stdout


def test_incarnations_never_correlate_across_restart_gap(tmp_path):
    """A restarted gang replays the same global step numbers; pairing
    rank 0's incarnation-1 records against rank 1's incarnation-0
    records would read the whole restart gap (seconds) as skew and name
    a healthy rank straggler."""
    i0 = tmp_path / "i0"
    i1 = tmp_path / "i1"
    i0.mkdir(), i1.mkdir()
    # incarnation 0: both ranks, balanced
    _write_rank(str(i0), 0, n=6)
    _write_rank(str(i0), 1, n=6)
    # incarnation 1: only rank 0 left telemetry, 30s later (restart gap)
    path = os.path.join(str(i1), "metrics.p0.jsonl")
    with open(path, "w") as f:
        for k in range(6):
            ts = 130.0 + STEP_S * k
            f.write(json.dumps({"kind": "step", "step": k,
                                "csig": "ab12cd34", "lane": 0,
                                "ts": ts, "ts_dispatch": ts}) + "\n")
    report = trace_merge.skew_from_dir(str(tmp_path))
    # only incarnation 0's steps correlate; the i1-vs-i0 30s gap is NOT
    # skew and no straggler is invented
    assert report["steps_correlated"] == 6
    assert all(e["incarnation"] == 0 for e in report["entries"])
    assert report["max_skew_s"] < 1.0
    assert "straggler" not in report


def test_incarnation_dirs_sort_numerically(tmp_path):
    # i10 must beat i9 as "newest", not sort between i1 and i2
    for k in (1, 9, 10):
        d = tmp_path / f"i{k}"
        d.mkdir()
        _write_trace(str(d), 0)
    files = trace_merge.find_rank_files(str(tmp_path))
    assert files["traces"][0].endswith("i10/trace.p0.json")


def test_cli_check_fails_on_empty_dir_even_with_out(tmp_path):
    empty = tmp_path / "empty"
    empty.mkdir()
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "trace_merge.py"),
         str(empty), "--out", str(tmp_path / "m.json"),
         "--check", "--max-step-skew-frac", "0.5"],
        capture_output=True, text=True)
    assert r.returncode == 1  # a gate with zero evidence must not pass


def test_torn_last_line_is_tolerated(tmp_path):
    d = str(tmp_path)
    _write_rank(d, 0)
    p1 = _write_rank(d, 1, lag_from=3, lag_s=PLANT_SKEW)
    with open(p1, "a") as f:
        f.write('{"kind": "step", "step": 99, "csi')  # SIGKILL mid-write
    report = trace_merge.skew_from_dir(d)
    assert report["steps_correlated"] == 12
    assert report["straggler"]["rank"] == 1
