"""PTQ/QAT int8 inference pipeline (VERDICT r4 #8).

Reference chain being mirrored: slim QAT (fake-quant instrumentation) ->
QuantizationFreezePass -> mkldnn_quantizer-style deployable int8 model ->
AnalysisConfig/AnalysisPredictor serving with ZeroCopyTensor handles.
"""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, optimizer
from paddle_tpu.contrib import slim
from paddle_tpu.contrib.slim.quantization import convert_quant_model
from paddle_tpu.core.program import Program, program_guard
from paddle_tpu.inference import AnalysisConfig, Predictor, create_predictor


def _build_net():
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("x", [3, 8, 8], dtype="float32")
        c = layers.conv2d(x, num_filters=8, filter_size=3, padding=1, act="relu")
        p = layers.pool2d(c, pool_size=2, pool_stride=2)
        flat = layers.reshape(p, [-1, 8 * 4 * 4])
        out = layers.fc(flat, 10, act="softmax")
    return main, startup, x, out


def _train_and_save(tmpdir, quantized, qat=False):
    main, startup, x, out = _build_net()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    startup.random_seed = 3
    exe.run(startup, scope=scope)
    if qat:
        # weight-only QAT: the deployed model drops activation fake-quants,
        # so only weight quantization survives into serving — instrument
        # what deployment keeps and the parity check below can be tight
        n = slim.quant_aware(main, weight_bits=8, quantize_activations=False)
        assert n > 0
    xv = np.random.RandomState(0).rand(4, 3, 8, 8).astype("f4")
    (ref,) = exe.run(main, feed={"x": xv}, fetch_list=[out], scope=scope)
    d = os.path.join(tmpdir, "q" if quantized else "f")
    if quantized:
        fluid.io.save_quantized_inference_model(d, ["x"], [out], exe, main, scope)
    else:
        fluid.io.save_inference_model(d, ["x"], [out], exe, main, scope)
    return d, xv, np.asarray(ref)


def test_convert_strips_fake_quant_and_snaps_weights():
    main, startup, x, out = _build_net()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    n = slim.quant_aware(main, weight_bits=8)
    assert n > 0
    types_before = [op.type for op in main.global_block().ops]
    assert any(t.startswith("fake_quantize") for t in types_before)
    manifest = convert_quant_model(main, scope, weight_bits=8)
    types_after = [op.type for op in main.global_block().ops]
    assert not any(t.startswith("fake_quantize") for t in types_after)
    assert manifest["weights"]
    # snapped weights sit exactly on the int8 grid (per-tensor scales here)
    for wname, rec in manifest["weights"].items():
        if rec["axis"] is not None:
            continue
        w = np.asarray(scope.find_var(wname))
        q = w / np.float32(rec["scale"]) * 127
        assert np.allclose(q, np.round(q), atol=1e-3)


def test_quantized_model_roundtrip_parity(tmp_path):
    d, xv, ref = _train_and_save(str(tmp_path), quantized=True)
    # int8 payloads on disk
    import json
    qman = json.load(open(os.path.join(d, "__quant__.json")))
    assert qman["weights"]
    for wname in qman["weights"]:
        arr = np.load(os.path.join(d, wname.replace("/", "%2F") + ".npy"))
        assert arr.dtype == np.int8
    cfg = AnalysisConfig(d, place=fluid.CPUPlace())
    pred = create_predictor(cfg)
    (got,) = pred.run({"x": xv})
    # documented tolerance: int8 weight grid on a small conv net
    assert np.allclose(got, ref, atol=0.05), np.abs(got - ref).max()
    # probabilities still sum to 1
    np.testing.assert_allclose(np.asarray(got).sum(-1), 1.0, rtol=1e-4)


def test_quantized_vs_float_predictor_close(tmp_path):
    df, xv, ref_f = _train_and_save(str(tmp_path), quantized=False)
    dq, _, _ = _train_and_save(str(tmp_path), quantized=True)
    pf = Predictor(AnalysisConfig(df, place=fluid.CPUPlace()))
    pq = Predictor(AnalysisConfig(dq, place=fluid.CPUPlace()))
    (a,) = pf.run({"x": xv})
    (b,) = pq.run({"x": xv})
    assert np.allclose(a, b, atol=0.05), np.abs(np.asarray(a) - np.asarray(b)).max()


def test_qat_to_deployed_int8(tmp_path):
    d, xv, ref = _train_and_save(str(tmp_path), quantized=True, qat=True)
    pred = Predictor(AnalysisConfig(d, place=fluid.CPUPlace()))
    (got,) = pred.run({"x": xv})
    # the QAT forward already saw the quantization error, so deploy matches
    # the instrumented program tightly
    assert np.allclose(got, ref, atol=1e-3), np.abs(got - ref).max()


def test_zero_copy_handles(tmp_path):
    d, xv, ref = _train_and_save(str(tmp_path), quantized=True)
    pred = Predictor(AnalysisConfig(d, place=fluid.CPUPlace()))
    assert pred.get_input_names() == ["x"]
    pred.get_input_handle("x").copy_from_cpu(xv)
    assert pred.run_zero_copy()
    out_h = pred.get_output_handle(pred.get_output_names()[0])
    got = out_h.copy_to_cpu()
    assert np.allclose(got, ref, atol=0.05)
    # device-resident pass-through: share a jax array, no host copy
    import jax.numpy as jnp
    pred.get_input_handle("x").share_external_data(jnp.asarray(xv))
    assert pred.run_zero_copy()
    got2 = out_h.copy_to_cpu()
    np.testing.assert_allclose(got, got2, rtol=1e-5)


def test_analysis_config_surface(tmp_path):
    d, _, _ = _train_and_save(str(tmp_path), quantized=False)
    cfg = (AnalysisConfig(d).disable_tpu().switch_ir_optim(False)
           .enable_memory_optim().set_cpu_math_library_num_threads(4)
           .enable_quantize())
    s = cfg.summary()
    assert s["place"] == "CPUPlace" and s["threads"] == 4
    p = Predictor(cfg)
    c = p.clone()
    assert c.scope is p.scope  # shared weights


def test_channel_wise_square_weight_axis(tmp_path):
    """Regression (r5 review): a SQUARE matmul weight with channel-wise
    scales must carry its quant_axis through save/load explicitly —
    shape-matching inference would pick the wrong axis and wrap int8."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("x", [16], dtype="float32")
        h = layers.fc(x, 16, param_attr=fluid.ParamAttr(name="sq.w"),
                      bias_attr=False)  # 16x16 square weight
        out = layers.fc(h, 4, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    startup.random_seed = 9
    exe.run(startup, scope=scope)
    # make per-column magnitudes very different so a wrong axis is loud
    w = np.asarray(scope.find_var("sq.w")).copy()
    w *= np.geomspace(0.01, 10.0, 16)[None, :]
    scope.set_var("sq.w", w.astype("f4"))
    from paddle_tpu.contrib.slim import quant_aware
    quant_aware(main, weight_bits=8, quantize_activations=False,
                weight_quantize_type="channel_wise_abs_max")
    xv = np.random.RandomState(1).rand(8, 16).astype("f4")
    (ref,) = exe.run(main, feed={"x": xv}, fetch_list=[out], scope=scope)
    d = str(tmp_path / "sq")
    fluid.io.save_quantized_inference_model(d, ["x"], [out], exe, main, scope)
    pred = Predictor(AnalysisConfig(d, place=fluid.CPUPlace()))
    (got,) = pred.run({"x": xv})
    assert np.allclose(got, np.asarray(ref), atol=1e-3), \
        np.abs(np.asarray(got) - np.asarray(ref)).max()


def test_quant_save_leaves_training_scope_bit_identical(tmp_path):
    """Regression (ISSUE 19 satellite, fix from r17): the quant passes
    snap weights to the int8 grid via scope.set_var while SAVING; the
    live training scope must be restored bit-identically afterwards —
    an online-learning loop keeps training this scope between publishes,
    so a silent int8 snap would poison every step after the first save."""
    main, startup, x, out = _build_net()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    startup.random_seed = 11
    exe.run(startup, scope=scope)
    xv = np.random.RandomState(2).rand(4, 3, 8, 8).astype("f4")
    (ref,) = exe.run(main, feed={"x": xv}, fetch_list=[out], scope=scope)
    before = {n: np.asarray(scope.find_var(n)).copy()
              for n in scope.local_var_names()}
    fluid.io.save_quantized_inference_model(
        str(tmp_path / "q"), ["x"], [out], exe, main, scope)
    after_names = set(scope.local_var_names())
    assert after_names == set(before), \
        f"quant save changed the scope's var set: {after_names ^ set(before)}"
    for n, b in before.items():
        a = np.asarray(scope.find_var(n))
        assert a.dtype == b.dtype, n
        np.testing.assert_array_equal(a, b, err_msg=f"var {n!r} mutated")
    # and the float forward pass still reproduces bit-identically
    (again,) = exe.run(main, feed={"x": xv}, fetch_list=[out], scope=scope)
    np.testing.assert_array_equal(np.asarray(again), np.asarray(ref))
