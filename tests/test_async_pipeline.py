"""Async dispatch pipeline (ISSUE 2): `Executor.run_async` lazy fetch
handles must be value-equivalent to synchronous `run`, surface in-flight
errors on resolution, and `pipeline.train_loop` must drive an overlapped
loop whose logged fetches match the serial loop's.  CPU-only, fast —
runs in tier-1."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.core.scope import RNG_STATE_VAR


def _build_sgd_program(seed=11):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, 8, act="relu")
        h = fluid.layers.dropout(h, dropout_prob=0.3)  # exercises RNG threading
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
        fluid.optimizer.Adam(1e-2).minimize(loss)
    startup.random_seed = seed
    main.random_seed = seed
    return main, startup, loss


def _feed_seq(n, batch=8):
    rng = np.random.RandomState(0)
    out = []
    for _ in range(n):
        xv = rng.rand(batch, 4).astype("f4")
        out.append({"x": xv, "y": xv.sum(1, keepdims=True)})
    return out


def _run_serial(feeds, loss, main, startup):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    losses = [exe.run(main, feed=f, fetch_list=[loss], scope=scope)[0]
              for f in feeds]
    return losses, scope


def test_run_async_matches_sync():
    """Handles resolve to the same values as a synchronous run over the
    same feed sequence; params, optimizer accumulators, and the RNG key
    advance identically (the scope chains output buffers, not handles)."""
    main, startup, loss = _build_sgd_program()
    feeds = _feed_seq(6)
    sync_losses, sync_scope = _run_serial(feeds, loss, main, startup)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    handle_seq = [exe.run_async(main, feed=f, fetch_list=[loss], scope=scope)
                  for f in feeds]  # all 6 steps dispatched before ANY resolve
    async_losses = [hs[0].numpy() for hs in handle_seq]

    for a, s in zip(async_losses, sync_losses):
        np.testing.assert_array_equal(a, s)
    for name in sync_scope.local_var_names():
        if name == RNG_STATE_VAR:
            continue
        np.testing.assert_array_equal(
            np.asarray(scope.find_var(name)),
            np.asarray(sync_scope.find_var(name)),
            err_msg=f"state var {name} diverged under async dispatch")
    np.testing.assert_array_equal(
        np.asarray(scope.find_var(RNG_STATE_VAR)),
        np.asarray(sync_scope.find_var(RNG_STATE_VAR)))


def test_run_async_interleaves_with_sync_run():
    """A sync run issued after async dispatches sees their state updates."""
    main, startup, loss = _build_sgd_program()
    feeds = _feed_seq(4)
    sync_losses, _ = _run_serial(feeds, loss, main, startup)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    for f in feeds[:3]:
        exe.run_async(main, feed=f, fetch_list=[loss], scope=scope)
    (last,) = exe.run(main, feed=feeds[3], fetch_list=[loss], scope=scope)
    np.testing.assert_array_equal(last, sync_losses[3])


def test_fetch_handle_api():
    x = fluid.layers.data("x", [3], dtype="float32")
    y = fluid.layers.scale(x, scale=3.0)
    exe = fluid.Executor(fluid.CPUPlace())
    (h,) = exe.run_async(feed={"x": np.ones((2, 3), "f4")}, fetch_list=[y])
    assert h.name == y.name
    h.wait()  # no host copy, just completion
    assert h.is_ready()
    np.testing.assert_allclose(np.asarray(h), np.full((2, 3), 3.0))
    np.testing.assert_allclose(h.numpy(), np.full((2, 3), 3.0))
    assert "resolved" in repr(h)


def test_run_async_nan_surfaces_on_resolution():
    """An in-flight NaN (FLAGS_check_nan_inf) raises at handle resolution,
    not dispatch, and every handle of the dispatch reports the same
    sticky error; the scope stays usable for subsequent runs."""
    x = fluid.layers.data("x", [2], dtype="float32")
    y = fluid.layers.scale(x, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        bad = np.array([[1.0, np.nan]], dtype="f4")
        (h,) = exe.run_async(feed={"x": bad}, fetch_list=[y], scope=scope)
        with pytest.raises(RuntimeError, match="NaN/Inf"):
            h.numpy()
        with pytest.raises(RuntimeError, match="NaN/Inf"):
            np.asarray(h)  # sticky: second access sees the same failure
        # scope not corrupted: a clean follow-up run works
        (ok,) = exe.run(feed={"x": np.ones((1, 2), "f4")}, fetch_list=[y],
                        scope=scope)
        np.testing.assert_allclose(ok, [[2.0, 2.0]])
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})


def test_train_loop_matches_serial_and_records_metrics():
    """CPU-only pipeline smoke test (tier-1): logged steps of the
    overlapped loop equal the serial loop's values; the monitor carries
    pipeline.inflight / host_blocked / pipeline_step records."""
    main, startup, loss = _build_sgd_program()
    feeds = _feed_seq(10)
    sync_losses, _ = _run_serial(feeds, loss, main, startup)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    monitor.reset()
    monitor.enable()
    try:
        stats = fluid.train_loop(exe, main, iter(feeds), [loss], scope=scope,
                                 max_inflight=3, log_period=3)
    finally:
        monitor.disable()
    assert stats.steps == 10
    assert [s for s, _ in stats.logged] == [0, 3, 6, 9]
    for step_i, vals in stats.logged:
        np.testing.assert_array_equal(vals[0], sync_losses[step_i])
    assert 1 <= stats.max_inflight_seen <= 3
    assert stats.wall_s > 0 and stats.host_blocked_s >= 0

    records = [r for r in monitor.step_records()
               if r.get("kind") == "pipeline_step"]
    assert len(records) == 10
    assert sum(1 for r in records if r["logged"]) == 4
    spans = monitor.get_monitor().span_stats()
    assert "pipeline.host_blocked" in spans
    assert "executor.dispatch" in spans
    assert monitor.gauge("pipeline.inflight").read() == 0  # drained
    # pipeline_step records describe the SAME steps the executor already
    # counted: executor.steps must not double-count them
    assert monitor.counter("executor.steps").value == 10


def test_train_loop_on_logged_callback_and_max_steps():
    main, startup, loss = _build_sgd_program()
    feeds = _feed_seq(8)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    seen = []
    stats = fluid.train_loop(exe, main, iter(feeds), [loss], scope=scope,
                             max_inflight=2, log_period=2,
                             on_logged=lambda s, v: seen.append(s),
                             max_steps=5)
    assert stats.steps == 5
    assert seen == [0, 2, 4]
    assert stats.logged == []  # callback consumed them


def test_train_loop_rejects_empty_fetch_list():
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(ValueError, match="fetch_list"):
        fluid.train_loop(exe, fluid.Program(), iter([]), [])


def test_perf_report_host_blocked_gate(tmp_path):
    """tools/perf_report.py --check gates on the pipeline's steady-state
    host-blocked fraction from MonitorLogger output."""
    import json
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent.parent))
    from tools.perf_report import check

    path = tmp_path / "metrics.jsonl"
    rows = [{"kind": "step", "recompiles_total": 1} for _ in range(6)]
    rows += [{"kind": "pipeline_step", "pipeline_step": i,
              "t_host_blocked_s": 0.02, "t_step_wall_s": 0.1,
              "inflight": 2, "logged": i % 2 == 0} for i in range(6)]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert check(str(path), max_host_blocked_frac=0.5) == 0
    assert check(str(path), max_host_blocked_frac=0.1) == 1  # frac = 0.2
    # threshold given but no pipeline records -> explicit failure
    bare = tmp_path / "bare.jsonl"
    bare.write_text("\n".join(json.dumps(r) for r in rows[:6]) + "\n")
    assert check(str(bare), max_host_blocked_frac=0.5) == 1
    assert check(str(bare)) == 0


def test_train_loop_drains_inflight_on_error():
    """If a drain raises mid-loop, the remaining in-flight handles must be
    waited on and discarded — not abandoned pinning device buffers — and
    the error must carry the failing step's index (ISSUE 3 satellite)."""
    from paddle_tpu.errors import NumericError, get_context

    main, startup, loss = _build_sgd_program()
    feeds = _feed_seq(8)
    feeds[2]["x"] = np.full_like(feeds[2]["x"], np.nan)  # poison step 2
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    monitor.reset()
    monitor.enable()
    try:
        with pytest.raises(NumericError, match="NaN/Inf") as ei:
            fluid.train_loop(exe, main, iter(feeds), [loss], scope=scope,
                             max_inflight=3, log_period=1)
    finally:
        monitor.disable()
        fluid.set_flags({"FLAGS_check_nan_inf": False})
    assert get_context(ei.value)["step"] == 2
    # nothing left in flight: the finally drained the abandoned handles
    assert monitor.gauge("pipeline.inflight").read() == 0
    # the executor/scope stay usable after the abort (params carry the
    # poison — recovery is the resilience layer's job — but runs succeed)
    (ok,) = exe.run(main, feed=_feed_seq(1)[0], fetch_list=[loss], scope=scope)
    assert ok.shape == (1,)


def test_train_loop_step_offset_and_dispatch_hook():
    """step_offset shifts logging phase and indices to GLOBAL numbering
    (what resilient segments rely on); on_dispatch fires before each
    dispatch with the feed."""
    main, startup, loss = _build_sgd_program()
    feeds = _feed_seq(6)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    seen = []
    stats = fluid.train_loop(exe, main, iter(feeds), [loss], scope=scope,
                             max_inflight=2, log_period=4, step_offset=10,
                             on_dispatch=lambda s, f: seen.append(s))
    assert stats.steps == 6
    assert seen == [10, 11, 12, 13, 14, 15]
    assert [s for s, _ in stats.logged] == [12]  # global 12 % 4 == 0


def test_dispatch_time_error_carries_step_context():
    """An exception raised synchronously inside run_async (compile/enqueue
    path) must carry the step index, same as resolution failures — the
    resilience layer's retry attribution depends on it."""
    from paddle_tpu.errors import get_context

    main, startup, loss = _build_sgd_program()
    feeds = _feed_seq(4)
    feeds[2] = {"x": feeds[2]["x"][:, :2], "y": feeds[2]["y"]}  # bad shape
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    with pytest.raises(Exception) as ei:
        fluid.train_loop(exe, main, iter(feeds), [loss], scope=scope,
                         max_inflight=2)
    assert get_context(ei.value)["step"] == 2
