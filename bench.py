"""Benchmark driver covering every BASELINE.md target (reference harness:
benchmark/fluid/fluid_benchmark.py — one driver, many models).

Default invocation prints ONE JSON line: the flagship ResNet-50 metric with
every other model's result embedded under extra.models.  `--per-model`
prints one JSON line per model instead (mnist parity gate, resnet50,
transformer NMT ragged path, BERT-base, DeepFM CTR).  `--pipeline` runs
the serial-vs-overlapped loop A/B (paddle_tpu.pipeline.train_loop +
Executor.run_async) and prints its own JSON line with both rates and
host-blocked fractions.  `--chaos` runs the resilient loop under a fixed
injected fault schedule (paddle_tpu.faults) and reports throughput plus
the recovery ledger — the robustness overhead as a number; a storage
spec (enospc@S / ro_fs@S / eio@N / slow_io@N:MS) routes to the
storage-fault A/B, reporting the degraded-window length, recovery
overhead, and the bit-identical-parity bit.  With a
distributed spec (kill_worker@S:RANK), `--elastic` adds the ISSUE-9 arm:
the same kill under elastic supervision (shrink to N-1, grow back),
reporting resize overhead and post-resize throughput next to the
fixed-size restart baseline.  `--serve` runs the closed-loop serving
load generator (paddle_tpu.serving): throughput vs p50/p99 tail latency
through the continuous-batching server plus an overload arm proving
admission-control shedding keeps p99 bounded — its JSONL metrics stream
is gated by `perf_report --check --max-shed-frac/--max-p99-ms`.
`--serve --quant` runs the fp32-vs-quantized serving A/B instead
(ISSUE 17): the int8/bf16 snapshot goes live through the full publish
ladder (accuracy-parity gate included) and the record carries both
arms' rps/p99, the HBM narrowing, and the parity ledger — gated by
`perf_report --check --require-quant-parity`.  `--chaos-campaign`
(ISSUE 20) runs the seeded multi-fault campaign engine
(paddle_tpu/chaos.py) over the train / online / serving scenarios —
pseudo-random compound schedules judged by the cross-subsystem
invariant registry, failures shrunk to minimal repro specs — and the
record carries the campaign ledger plus the `perf_report --check
--max-chaos-violations 0` verdict on its own metrics stream.

vs_baseline: the reference published no numbers (BASELINE.md), so the
absolute series is tracked across rounds; vs_baseline = this round's
imgs/s over round-1's 2295.

MFU numbers are computed from analytic FLOPs (the tunnel backend's
cost_analysis() is broken — returns 4.2 GFLOP for a full ResNet train
step); labeled `*_analytic`.
"""
from __future__ import annotations

import json
import sys
import time as _time

import numpy as np

from tools.bench_kit import (make_bert_dispatch, make_resnet_dispatch,
                             spread_pct as _spread, timed_steps as _timed_steps)
# ONE spread ceiling, shared with the --check-bench gate: ratcheting it in
# perf_report ratchets the warm-until-stable target here in lockstep
from tools.perf_report import MAX_SPREAD_PCT

ROUND1_IMGS_PER_SEC = 2295.0  # BENCH_r01.json
V5E_BF16_PEAK = 197e12


def _predicted_roofline(dispatch):
    """The program's OWN static roofline MFU (core/resource_plan.py) for
    the EXACT program + feed shapes this dispatch measured (bench_kit
    attaches them) — the denominator perf_report --check-bench prints
    measured MFU against, so a number far under roofline is named instead
    of averaged away.  None when planning fails (a plan bug must never
    block a bench round)."""
    try:
        from paddle_tpu.core.resource_plan import plan_program

        plan = plan_program(dispatch.main_program, dispatch.feed_shapes,
                            [dispatch.loss_name], steps=dispatch.steps)
        return round(plan.predicted_mfu, 4)
    except Exception as e:  # pragma: no cover - defensive
        print(f"bench: roofline prediction failed: {e!r}", file=sys.stderr)
        return None


def _params_moved(dispatch, before, max_frozen_frac=0.25):
    """Bench-level optimizer-liveness gate (the r5 bf16+Adam freeze shipped
    two rounds of plausible-looking BERT numbers with ~96% of params frozen
    while the f32 embeddings moved — loss finiteness cannot catch that).

    ISSUE-7 resolution of BENCH_r05's "18/198 BERT params frozen": the
    donation audit (tools/donation_audit.py) proves every zoo param is
    donated and updated in place, so a zero param delta with a LIVE
    first-order moment means the optimizer ran and the update rounded away
    below the param dtype's resolution — exactly the bf16 q/k stall at
    symmetric init (score grads cancel below bf16 ulp for the first steps;
    measured r5, docs/perf_r05.md).  Those now count as `subresolution`,
    not `frozen`; a param whose MOMENT is also dead is a genuinely dropped
    update, and any such param fails the bench outright
    (tests/test_donation_audit.py pins both classes).

    Known ambiguity, strict on purpose: a param whose gradient is EXACTLY
    zero for the whole window (dead ReLU unit) also shows a dead moment and
    trips the hard fail.  After the r5 silent-freeze history we prefer the
    loud false positive: if tools/donation_audit.py --check is green, the
    param is a genuinely zero-gradient unit — re-bench with a different
    seed/batch rather than raising the tolerance here."""
    after = dispatch.probe_param()
    moments = (dispatch.probe_moments()
               if hasattr(dispatch, "probe_moments") else {})
    frozen, subres = [], []
    min_moved = float("inf")
    for name, b in before.items():
        d = float(np.abs(after[name] - b).max())
        if d == 0.0:
            m = moments.get(name)
            if m is None:
                # no first-order accumulator to consult (SGD-class
                # optimizers keep none): a zero delta here is
                # indistinguishable from a legitimately-zero gradient, so
                # it counts against the bounded budget, not the hard fail
                subres.append(name)
            elif float(np.abs(m).max()) > 0.0:
                subres.append(name)  # optimizer live, update < dtype ulp
            else:
                frozen.append(name)
        else:
            min_moved = min(min_moved, d)
    assert not frozen, (
        f"{len(frozen)}/{len(before)} params have DEAD optimizer state "
        f"(dropped-update class bug — see tools/donation_audit.py): "
        f"{sorted(frozen)[:5]}")
    assert len(subres) <= max_frozen_frac * len(before), (
        f"{len(subres)}/{len(before)} params sat below update resolution "
        f"(or have no optimizer accumulator to consult) during the bench "
        f"window: {sorted(subres)[:5]}")
    assert min_moved < float("inf"), "no param moved at all"
    return {"frozen": len(frozen), "subresolution": len(subres),
            "total": len(before), "min_moved_delta": min_moved}


def _gang_results(res):
    """Every RESULT-line JSON record printed by a gang's workers (the
    worker output protocol shared by the overlap and chaos A/Bs)."""
    recs = []
    for code, out, err in res.workers:
        for line in (out or "").splitlines():
            if line.startswith("RESULT "):
                recs.append(json.loads(line[len("RESULT "):]))
    return recs


def _gang_skew(res):
    """Embed the gang's cross-rank skew record (ISSUE 8): the workers
    streamed rank-tagged step records into run_gang's telemetry dir, so
    tools/trace_merge.py can correlate them and name the round's
    straggler.  {} when fewer than two ranks left telemetry (e.g. a rank
    died before its first step) — `perf_report --check-bench` gates the
    fields only when present."""
    try:
        from tools.trace_merge import skew_from_dir

        rep = skew_from_dir(res.telemetry_dir) if res.telemetry_dir else None
    except Exception:
        rep = None
    if not rep or not rep.get("steps_correlated"):
        return {}
    out = {"step_skew_frac": rep.get("mean_skew_frac"),
           "max_step_skew_frac": rep.get("max_skew_frac"),
           "skew_steps_correlated": rep.get("steps_correlated"),
           "straggler_rank": rep.get("straggler", {}).get("rank")}
    return {k: v for k, v in out.items() if v is not None}


def bench_resnet50(batch_size=128, K=16, iters=4):
    # bs128/K=16 interleaved-A/B'd vs bs256/K8 and bs64/K32: 2573 vs 2445
    # vs 2351 imgs/s — the r4 "bs256 wins" result predates the single-pass
    # BN stats; with less stats traffic the smaller batch's better
    # cache/VMEM behavior wins (docs/perf_r05.md)
    dispatch, _ = make_resnet_dispatch(batch_size=batch_size, K=K)
    before = dispatch.probe_param()
    dt, out, ws = _timed_steps(dispatch, K=K, iters=iters, windows=3,
                               spread_target=MAX_SPREAD_PCT)
    lossN = float(np.asarray(out[0]).reshape(-1)[-1])
    assert np.isfinite(lossN), f"non-finite resnet loss {lossN}"
    moved = _params_moved(dispatch, before)
    imgs = batch_size / dt
    mfu = imgs * 3 * 4.089e9 / V5E_BF16_PEAK
    print(f"resnet50: {dt*1e3:.1f} ms  {imgs:.0f} imgs/s  mfu {mfu:.3f}", file=sys.stderr)
    pred = _predicted_roofline(dispatch)
    return {"metric": "resnet50_train_imgs_per_sec_per_chip", "value": round(imgs, 2),
            "unit": "imgs/sec", "mfu_bf16_analytic": round(mfu, 4),
            "mfu_predicted_roofline": pred,
            "batch_size": batch_size, "steps_per_dispatch": K,
            "params_moved": moved,
            "windows_ms": ws, "spread_pct": _spread(ws)}


def bench_mnist(batch_size=128, steps=40, K=20, iters=3):
    """Loss-parity gate (BASELINE: 'loss parity vs CPU ref'): the same
    seeded program must converge on the chip and match a rerun bit-for-bit
    modulo accelerator numerics (rtol 1e-3 on the loss curve).  Throughput
    is a separate steps=K scan with device-resident feeds — the per-step
    host loop below measures the parity curve, not the chip."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.models import mnist

    rng = np.random.RandomState(0)
    # strongly learnable synthetic task (random labels would floor the CE
    # at ln10): each class k brightens the image by 0.06*k, so class is
    # linearly decodable from mean brightness and the net leaves the prior
    # floor within a few dozen steps
    labels = rng.randint(0, 10, (steps, batch_size)).astype("int64")
    imgs = (rng.rand(steps, batch_size, 1, 28, 28) * 0.4
            + labels[..., None, None, None] * 0.06).astype("float32")
    labels = labels[..., None]

    def run(place):
        main, startup, feeds, fetches = mnist.build(learning_rate=1e-3)
        startup.random_seed = 7
        scope = fluid.Scope()
        exe = fluid.Executor(place)
        exe.run(startup, scope=scope)
        losses = []
        for i in range(steps):
            (lv,) = exe.run(main, feed={"img": imgs[i], "label": labels[i]},
                            fetch_list=[fetches["loss"]], scope=scope)
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        return losses

    tpu_losses = run(fluid.TPUPlace(0))
    cpu_losses = run(fluid.CPUPlace())
    parity = bool(np.allclose(tpu_losses, cpu_losses, rtol=5e-2, atol=1e-3))
    converged = tpu_losses[-1] < tpu_losses[0] * 0.7

    # steady-state throughput: K optimizer steps per dispatch
    main, startup, feeds, fetches = mnist.build(learning_rate=1e-3)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    dev = fluid.TPUPlace(0).jax_device()
    feed = {
        "img": jax.device_put(jnp.asarray(imgs[:K]), dev),
        "label": jax.device_put(jnp.asarray(labels[:K], jnp.int32), dev),
    }
    loss_name = fetches["loss"].name

    def dispatch():
        return exe.run(main, feed=feed, fetch_list=[loss_name], scope=scope,
                       steps=K, return_numpy=False)

    dt, out, ws = _timed_steps(dispatch, K=K, iters=iters, windows=3,
                               spread_target=MAX_SPREAD_PCT)
    imgs_per_sec = batch_size / dt
    print(f"mnist: parity={parity} converged={converged} "
          f"loss {tpu_losses[0]:.3f}->{tpu_losses[-1]:.3f}  "
          f"{imgs_per_sec:.0f} imgs/s", file=sys.stderr)
    return {"metric": "mnist_loss_parity", "value": round(imgs_per_sec, 2),
            "unit": "imgs/sec", "parity_vs_cpu": parity, "converged": bool(converged),
            "first_loss": round(tpu_losses[0], 4), "last_loss": round(tpu_losses[-1], 4),
            "steps_per_dispatch": K, "windows_ms": ws, "spread_pct": _spread(ws)}


def bench_nmt(K=8, iters=3, b=32):
    """Transformer-base NMT on the ragged/LoD path: seqs/sec with
    variable-length batches (BASELINE: 'no CUDA ops in executed program' —
    trivially true: every op lowers to XLA).

    Measurement (r5): K steps per dispatch with device-resident pre-padded
    feeds + `<name>@LOD` lengths companions (tools.bench_kit.
    make_nmt_dispatch) — the executed program is the SAME ragged program,
    but the harness no longer measures per-step dispatch over the tunnel,
    which is what capped r3/r4 at ~250 seqs/s."""
    from tools.bench_kit import make_nmt_dispatch

    dispatch, _, mean_tokens = make_nmt_dispatch(K=K, b=b)
    before = dispatch.probe_param()
    # warmup-until-stable windowing (ISSUE 7): BENCH_r05's 26.3% NMT spread
    # was the first window still carrying warm-in (30.3 -> 22.8 ms); windows
    # now extend until the trailing 3 agree to 5%, so kernel A/Bs on this
    # config compare steady state against steady state.  spread_ok is the
    # self-check the record carries (and perf_report's bench gate can read).
    dt, out, ws = _timed_steps(dispatch, K=K, iters=iters, windows=3,
                               spread_target=MAX_SPREAD_PCT)
    lv = float(np.asarray(out[0]).reshape(-1)[-1])
    assert np.isfinite(lv)
    moved = _params_moved(dispatch, before)
    seqs = b / dt
    toks = mean_tokens * seqs
    print(f"nmt: {dt*1e3:.1f} ms  {seqs:.0f} seqs/s  loss {lv:.3f}", file=sys.stderr)
    return {"metric": "transformer_nmt_train_seqs_per_sec_per_chip",
            "value": round(seqs, 2), "unit": "seqs/sec", "batch_size": b,
            "config": "base-6L-512d ragged", "tokens_per_sec": round(toks, 1),
            "params_moved": moved,
            "steps_per_dispatch": K, "windows_ms": ws,
            "spread_pct": _spread(ws), "spread_ok": _spread(ws) <= MAX_SPREAD_PCT}


def bench_bert(batch_size=256, seq_len=128, K=2, iters=4):
    dispatch, _ = make_bert_dispatch(batch_size=batch_size, seq_len=seq_len, K=K)
    before = dispatch.probe_param()
    dt, out, ws = _timed_steps(dispatch, K=K, iters=iters, windows=2,
                               spread_target=MAX_SPREAD_PCT)
    lossN = float(np.asarray(out[0]).reshape(-1)[-1])
    assert np.isfinite(lossN)
    moved = _params_moved(dispatch, before)
    seqs = batch_size / dt
    # analytic train FLOPs/seq for BERT-base @128: ~6 * 110e6 params * 128 tokens
    flops_per_seq = 6 * 110e6 * seq_len
    mfu = seqs * flops_per_seq / V5E_BF16_PEAK
    print(f"bert: {dt*1e3:.1f} ms  {seqs:.0f} seqs/s  mfu {mfu:.3f}", file=sys.stderr)
    pred = _predicted_roofline(dispatch)
    return {"metric": "bert_base_train_seqs_per_sec_per_chip", "value": round(seqs, 2),
            "unit": "seqs/sec", "mfu_bf16_analytic": round(mfu, 4),
            "mfu_predicted_roofline": pred,
            "batch_size": batch_size, "seq_len": seq_len,
            "config": "fused-attention (output-dropout substitution)",
            "params_moved": moved,
            "steps_per_dispatch": K, "windows_ms": ws, "spread_pct": _spread(ws)}


def bench_deepfm(batch_size=4096, K=16, iters=3):
    """DeepFM CTR with sparse LookupTable grads.  r5: K steps per dispatch +
    device-resident feeds + windows/spread — the r4 harness (one exe.run per
    step, host feeds, no windows) was dominated by tunnel dispatch and swung
    90k..165k ex/s run-to-run on identical code (docs/perf_r05.md)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.core import lowering
    from paddle_tpu.models import deepfm

    main, startup, feeds, fetches = deepfm.build(
        num_fields=26, vocab_size=200000, embed_dim=16, mlp_dims=(400, 400, 400),
        learning_rate=0.05)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    dev = fluid.TPUPlace(0).jax_device()
    feed = {
        "feat_ids": jax.device_put(
            jnp.asarray(rng.randint(0, 200000, (K, batch_size, 26)), jnp.int32), dev),
        "label": jax.device_put(
            jnp.asarray((rng.rand(K, batch_size, 1) < 0.3), jnp.float32), dev),
    }

    def dispatch():
        return exe.run(main, feed=feed, fetch_list=[fetches["loss"]], scope=scope,
                       steps=K, return_numpy=False)

    from tools.bench_kit import attach_param_probe

    attach_param_probe(dispatch, main, scope)
    dispatch()  # compile before the probe so 'before' is post-init state
    before = dispatch.probe_param()
    dt, out, ws = _timed_steps(dispatch, K=K, iters=iters, windows=3,
                               spread_target=MAX_SPREAD_PCT)
    lossN = float(np.asarray(out[0]).reshape(-1)[-1])
    assert np.isfinite(lossN)
    moved = _params_moved(dispatch, before)
    sparse = sorted(lowering.LAST_TRACE_REPORT.get("sparse_grad_params", []))
    ex = batch_size / dt
    print(f"deepfm: {dt*1e3:.2f} ms  {ex:.0f} ex/s  sparse={sparse}", file=sys.stderr)
    return {"metric": "deepfm_ctr_train_examples_per_sec_per_chip",
            "value": round(ex, 2), "unit": "examples/sec",
            "batch_size": batch_size, "vocab": 200000,
            "sparse_grad_params": sparse, "steps_per_dispatch": K,
            "params_moved": moved,
            "windows_ms": ws, "spread_pct": _spread(ws)}


def bench_pipeline(batch_size=128, steps=24, max_inflight=4, log_period=8,
                   n_distinct_batches=4):
    """Serial `exe.run` loop vs `pipeline.train_loop` A/B over identical
    DataLoader-staged ResNet-50 batches (the ISSUE-2 overlap win).

    Both arms pull device-resident feeds from the same DataLoader config
    (H2D in the producer thread), so the A/B isolates the dispatch/fetch
    overlap: the serial arm resolves every step's fetch before dispatching
    the next, the pipelined arm keeps `max_inflight` steps in flight and
    resolves only every `log_period`-th.  Reports both rates plus each
    arm's host-blocked fraction — the pipelined one must sit strictly
    below the serial one (and does, or this bench is the regression
    alarm)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import monitor, pipeline
    from paddle_tpu.models import resnet

    main_p, startup, feeds, fetches = resnet.build(
        dtype="bfloat16", class_dim=1000, learning_rate=0.1,
        with_optimizer=True, stem="space_to_depth")
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    loss_name = fetches["loss"].name
    dev = fluid.TPUPlace(0).jax_device()
    rng = np.random.RandomState(0)
    batches = [
        {"img": rng.rand(batch_size, 3, 224, 224).astype("float32"),
         "label": rng.randint(0, 1000, (batch_size, 1)).astype("int64")}
        for _ in range(n_distinct_batches)
    ]

    def make_loader():
        def gen():
            for i in range(steps):
                yield batches[i % n_distinct_batches]

        return fluid.DataLoader.from_generator(
            [feeds["img"], feeds["label"]], capacity=max_inflight + 2,
            device=dev).set_batch_generator(gen)

    # warmup/compile outside both timing windows (same executable serves
    # both arms: same program, feed signature, and scope)
    exe.run(main_p, feed=batches[0], fetch_list=[loss_name], scope=scope)

    monitor.reset()
    monitor.enable()
    t0 = _time.perf_counter()
    last = None
    for feed in make_loader():
        (last,) = exe.run(main_p, feed=feed, fetch_list=[loss_name],
                          scope=scope)
    serial_wall = _time.perf_counter() - t0
    spans = monitor.get_monitor().span_stats()
    serial_blocked = (spans.get("executor.execute", {}).get("total_s", 0.0)
                      + spans.get("executor.fetch", {}).get("total_s", 0.0))
    serial_frac = serial_blocked / serial_wall if serial_wall else 0.0
    assert np.isfinite(float(np.asarray(last).reshape(-1)[0]))

    monitor.reset()
    stats = pipeline.train_loop(exe, main_p, make_loader(), [loss_name],
                                scope=scope, max_inflight=max_inflight,
                                log_period=log_period)
    monitor.disable()
    for _, vals in stats.logged:
        assert np.isfinite(float(np.asarray(vals[0]).reshape(-1)[0]))

    serial_imgs = steps * batch_size / serial_wall
    piped_imgs = steps * batch_size / stats.wall_s
    print(f"pipeline: serial {serial_imgs:.0f} imgs/s (host-blocked "
          f"{serial_frac:.3f})  pipelined {piped_imgs:.0f} imgs/s "
          f"(host-blocked {stats.host_blocked_frac:.3f})", file=sys.stderr)
    return {"metric": "resnet50_pipeline_overlap",
            "value": round(piped_imgs, 2), "unit": "imgs/sec",
            "serial_imgs_per_sec": round(serial_imgs, 2),
            "pipelined_imgs_per_sec": round(piped_imgs, 2),
            "speedup": round(piped_imgs / serial_imgs, 4) if serial_imgs else 0.0,
            "host_blocked_frac_serial": round(serial_frac, 4),
            "host_blocked_frac_pipelined": round(stats.host_blocked_frac, 4),
            "overlap_confirmed": bool(stats.host_blocked_frac < serial_frac),
            "batch_size": batch_size, "steps": steps,
            "max_inflight": max_inflight, "log_period": log_period}


def _serve_roofline(model_dir, rows):
    """The saved serving program's own static roofline at the `rows`-row
    bucket (core/resource_plan.py over the inference graph): the
    predicted-MFU denominator the serve record stamps
    (`mfu_predicted_roofline`, same meaning as the train records') plus
    the analytic per-row forward FLOPs the measured serving MFU is
    computed from.  {} when planning fails — a plan bug must never block
    a serve round."""
    import os

    try:
        from paddle_tpu.core.program import Program
        from paddle_tpu.core.resource_plan import plan_program
        from paddle_tpu.serving.registry import synthetic_feed_shapes

        with open(os.path.join(model_dir, "__model__.json")) as f:
            doc = json.load(f)
        program = Program.from_dict(doc)
        shapes = synthetic_feed_shapes(program, doc.get("feed_names", []),
                                       rows)
        plan = plan_program(program, shapes, doc.get("fetch_names", []))
        return {"mfu_predicted_roofline": round(plan.predicted_mfu, 4),
                "flops_per_row_analytic": plan.flops_total / max(rows, 1)}
    except Exception as e:  # pragma: no cover - defensive
        print(f"bench: serve roofline prediction failed: {e!r}",
              file=sys.stderr)
        return {}


# The serve bench's timed window must dwarf a CPython gen2 GC pause: at
# the old default of 400 requests the window was ~0.15 s, ONE collection
# landing inside it (steered by import order, nothing else) read as a
# ~20% rps regression and burned a PR-12 bisect.  Gen2 is frozen around
# the windows below AND the window length is asserted, so the bench
# physically cannot report a pause as a regression again.
MIN_SERVE_WINDOW_S = 1.0


class _gc_quiesced:
    """Freeze the current heap out of gen2's reach and disable automatic
    collection for the duration of a timed window; one explicit collect
    on entry starts the window clean."""

    def __enter__(self):
        import gc

        gc.collect()
        gc.freeze()
        gc.disable()
        return self

    def __exit__(self, *exc):
        import gc

        gc.enable()
        gc.unfreeze()


def bench_serve(requests=4000, clients=6, buckets=(1, 2, 4, 8),
                max_queue=64, overload_clients=12, overload_queue=4,
                overload_burst=6, overload_bursts=8, p99_gate_ms=2000.0,
                metrics_path=None, min_window_s=MIN_SERVE_WINDOW_S):
    """Closed-loop serving load generator (ISSUE 11): throughput vs tail
    latency through `paddle_tpu.serving.Server`, plus an OVERLOAD arm
    proving admission control keeps p99 bounded by shedding.

    Baseline arm: `clients` closed-loop threads (one outstanding request
    each, random 1..4-row batches — novel sizes on purpose) drive
    `requests` total requests; the record carries rps, p50/p99, and the
    steady-state recompile delta, which MUST be zero (every size serves
    from a warmed pad-to-bucket executable — the no-inline-recompile
    acceptance).

    Overload arm: a second server over the SAME registry (warm cache)
    with a tiny queue bound; `overload_clients` threads submit bursts so
    offered load exceeds capacity.  Shed requests are the designed
    response — the record reports the exact shed ledger and the p99 the
    survivors saw, gated against `p99_gate_ms` (unbounded queueing is
    what this arm would catch).

    Timed-window hardening (ISSUE 14 satellite): both arms run with gen2
    GC frozen+disabled (`_gc_quiesced`) and the baseline window must
    clear `MIN_SERVE_WINDOW_S` — the PR-12 false ~20% regression was ONE
    gen2 pause inside a ~0.15 s window at the old requests=400 default.

    Each arm gets its OWN metrics stream (`metrics_path` for baseline,
    `<metrics_path>.overload.jsonl` for the flood): the overload arm's
    mass shedding is designed, and folding it into the baseline stream
    would make the documented tight gate
    (`perf_report --check --max-shed-frac 0.05`) unusable on the bench's
    own output.  Gate the baseline file tight on sheds and the overload
    file loose on sheds / tight on p99."""
    import os
    import tempfile
    import threading

    import paddle_tpu as fluid
    from paddle_tpu import layers, monitor, serving
    from paddle_tpu.monitor import MonitorLogger

    rng = np.random.RandomState(0)
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = layers.data("x", [64], dtype="float32")
        h = layers.fc(x, 128, act="relu")
        out = layers.fc(h, 10, act="softmax")
    startup.random_seed = 7
    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    model_dir = tempfile.mkdtemp(prefix="pt-serve-bench-")
    fluid.io.save_inference_model(model_dir, ["x"], [out], exe, main_p, scope)

    if metrics_path is None:
        metrics_path = os.path.join(model_dir, "serve_metrics.jsonl")
    monitor.reset()
    monitor.enable()

    registry = serving.ModelRegistry(place=fluid.TPUPlace(0))
    srv = serving.Server(registry, buckets=buckets, max_queue=max_queue)
    srv.load_model("m", model_dir)  # warms every bucket
    rec0 = monitor.counter("executor.recompile").value
    miss0 = monitor.counter("executor.cache_miss").value
    # the metrics stream starts AFTER the load-time compile lane: steady
    # state is what the recompile-flat gate (and this bench's own zero-
    # recompile assert) holds to — warm compiles are the paid-once cost
    logger = monitor.attach_logger(MonitorLogger(metrics_path))

    served = [0]
    lock = threading.Lock()

    def client(seed):
        r = np.random.RandomState(seed)
        while True:
            with lock:
                if served[0] >= requests:
                    return
                served[0] += 1
            rows = int(r.randint(1, 5))
            srv.infer("m", {"x": r.rand(rows, 64).astype("f4")})

    threads = [threading.Thread(target=client, args=(i,)) for i in range(clients)]
    with _gc_quiesced():
        t0 = _time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = _time.perf_counter() - t0
    # min_window_s=0 is for tier-1 SMOKES only (they test plumbing, not
    # throughput); any measured round keeps the floor
    assert wall >= min_window_s, (
        f"serve bench timed window {wall*1e3:.0f} ms is shorter than the "
        f"{min_window_s:.1f} s floor — a window this size is "
        f"GC-pause-sized and its rps is noise; raise `requests` "
        f"(currently {requests}) until the window clears the floor")
    lat = srv.latency_ms()
    base_stats = srv.stats()
    # per-bucket queue/pad/compute attribution (ISSUE 16): read before
    # stop() like the stats — the record embeds where the latency went
    base_attr = srv.bucket_attribution()
    recompiles = monitor.counter("executor.recompile").value - rec0
    misses = monitor.counter("executor.cache_miss").value - miss0
    # snapshot BEFORE stop(): stop releases the server's lazy p50/p99
    # gauges, and the baseline file's gate reads them from the snapshot
    logger.write_snapshot()
    monitor.detach_logger(logger)
    srv.stop()
    assert recompiles == 0 and misses == 0, (
        f"steady-state serving compiled inline ({recompiles} recompiles, "
        f"{misses} cache misses) — the pad-to-bucket policy broke")

    # -- overload arm (its own stream: designed sheds must not pollute
    # the baseline file's gate) -------------------------------------------
    ov_metrics = metrics_path + ".overload.jsonl"
    ov_logger = monitor.attach_logger(MonitorLogger(ov_metrics))
    ov = serving.Server(registry, buckets=buckets, max_queue=overload_queue)
    offered = [0]
    shed = [0]

    def flood(seed):
        r = np.random.RandomState(1000 + seed)
        for _ in range(overload_bursts):
            futs = []
            for _ in range(overload_burst):
                with lock:
                    offered[0] += 1
                try:
                    futs.append(ov.submit(
                        "m", {"x": r.rand(int(r.randint(1, 5)), 64).astype("f4")}))
                except fluid.errors.ServingError as e:
                    assert e.reason == "overload", e
                    with lock:
                        shed[0] += 1
            for f in futs:
                f.result(timeout=60)

    threads = [threading.Thread(target=flood, args=(i,))
               for i in range(overload_clients)]
    with _gc_quiesced():
        t0 = _time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ov_wall = _time.perf_counter() - t0
    ov_lat = ov.latency_ms()
    ov_stats = ov.stats()
    ov_attr = ov.bucket_attribution()
    ov_logger.write_snapshot()  # before stop: gauges still armed
    monitor.detach_logger(ov_logger)
    ov.stop()
    assert ov_stats["shed"] == shed[0], "shed ledger drifted from clients'"
    monitor.disable()

    rps = requests / wall
    shed_frac = shed[0] / offered[0] if offered[0] else 0.0
    print(f"serve: {rps:.0f} req/s p50 {lat['p50']:.1f} ms p99 "
          f"{lat['p99']:.1f} ms (recompiles {recompiles}); overload: "
          f"{ov_stats['completed']}/{offered[0]} served, {shed[0]} shed "
          f"({shed_frac:.2%}), p99 {ov_lat['p99']:.1f} ms", file=sys.stderr)
    # measured-vs-predicted MFU stamps (ISSUE 17 satellite): the serving
    # program's own static roofline is the denominator perf_report
    # --check-bench prints measured MFU against — same contract as the
    # train records, so serving gaps are named, not averaged away
    import jax as _jax

    roof = _serve_roofline(model_dir, max(buckets))
    rows_per_sec = base_stats["rows"] / wall
    mfu = (rows_per_sec * roof["flops_per_row_analytic"] / V5E_BF16_PEAK
           if roof.get("flops_per_row_analytic") else None)
    return {"metric": "serving_closed_loop_rps", "value": round(rps, 2),
            "unit": "req/sec",
            "device": _jax.default_backend(),
            "mfu_bf16_analytic": round(mfu, 6) if mfu is not None else None,
            "mfu_predicted_roofline": roof.get("mfu_predicted_roofline"),
            "window_s": round(wall, 3), "min_window_s": min_window_s,
            "gc_frozen": True,
            "requests": requests, "clients": clients,
            "buckets": list(buckets), "max_queue": max_queue,
            "p50_ms": lat["p50"], "p99_ms": lat["p99"],
            "rows_per_sec": round(base_stats["rows"] / wall, 1),
            "batches": base_stats["batches"],
            "mean_batch_occupancy": round(
                base_stats["rows"] / max(
                    base_stats["rows"] + base_stats["padded_rows"], 1), 4),
            "recompiles_steady": recompiles,
            "cache_misses_steady": misses,
            # latency/pad attribution + SLO burn (ISSUE 16): queue-wait
            # share of completed requests' wall time, per-bucket ledger
            # (JSON keys are strings), and the windowed SLO accounting
            "queue_wait_frac": base_stats["queue_wait_frac"],
            "slo": base_stats["slo"],
            "bucket_attribution": {str(b): a for b, a in base_attr.items()},
            "overload": {
                "offered": offered[0], "completed": ov_stats["completed"],
                "shed": shed[0], "shed_frac": round(shed_frac, 4),
                "p99_ms": ov_lat["p99"],
                "p99_bounded": bool(ov_lat["p99"] <= p99_gate_ms),
                "p99_gate_ms": p99_gate_ms, "queue_bound": overload_queue,
                "req_per_sec": round((offered[0] - shed[0]) / ov_wall, 2),
                "queue_wait_frac": ov_stats["queue_wait_frac"],
                "slo": ov_stats["slo"],
                "bucket_attribution": {str(b): a
                                       for b, a in ov_attr.items()},
                "metrics_path": ov_metrics,
            },
            "metrics_path": metrics_path}


def bench_serve_quant(requests=4000, clients=4, buckets=(1, 2, 4, 8),
                      max_queue=64, serve_dtype="bfloat16", weight_bits=8,
                      metrics_path=None, min_window_s=MIN_SERVE_WINDOW_S):
    """fp32-vs-quantized serving A/B (ISSUE 17): the same model served
    twice through the bucketed server — once from its fp32
    save_inference_model dir, once from the int8
    save_quantized_inference_model dir whose weights dequantize into
    `serve_dtype` (bf16: half the resident weight HBM, int8-grid
    numerics).  The quant arm goes live through the FULL publish ladder,
    so the round exercises the accuracy-parity gate
    (FLAGS_serving_quant_atol vs the fp32 parent's outputs) for real —
    the record embeds the gate's own `quant_parity` event next to a
    direct fp32-vs-quant output comparison (the parity ledger).

    Honesty contract: rps/p99 are chip numbers ONLY on TPU.  Off-device
    the record still lands — parity ledger, HBM narrowing, and precision
    plumbing are platform-independent — but `throughput_claim` says
    `parity_only_off_device` and no floor may ratchet from it.

    The metrics stream starts AFTER the fp32 arm, so one file carries the
    quant publish lane (its warm compiles are the paid-once head of the
    stream), the `quant_parity` gate event, and the quant arm's
    steady-state serving steps.  Gate it with BOTH serving gates::

        python tools/perf_report.py --check <metrics_path> \\
            --steady-after <gate_steady_after> --require-quant-parity

    where `gate_steady_after` is embedded in the record (the measured
    publish-lane step count plus margin): past it the recompile-flat
    gate holds over the quant arm, which this bench also asserts
    directly (`recompiles_steady` must be 0)."""
    import os
    import tempfile
    import threading

    import jax as _jax

    import paddle_tpu as fluid
    from paddle_tpu import layers, monitor, serving
    from paddle_tpu.monitor import MonitorLogger

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = layers.data("x", [64], dtype="float32")
        h = layers.fc(x, 128, act="relu")
        out = layers.fc(h, 10, act="softmax")
    startup.random_seed = 7
    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    root = tempfile.mkdtemp(prefix="pt-serve-quant-")
    fp32_dir = os.path.join(root, "fp32")
    quant_dir = os.path.join(root, "quant")
    fluid.io.save_inference_model(fp32_dir, ["x"], [out], exe, main_p, scope)
    fluid.io.save_quantized_inference_model(
        quant_dir, ["x"], [out], exe, main_p, scope,
        weight_bits=weight_bits, serve_dtype=serve_dtype)

    if metrics_path is None:
        metrics_path = os.path.join(root, "serve_quant_metrics.jsonl")
    monitor.reset()
    monitor.enable()
    registry = serving.ModelRegistry(place=fluid.TPUPlace(0))

    lock = threading.Lock()

    def window(srv):
        served = [0]

        def client(seed):
            r = np.random.RandomState(seed)
            while True:
                with lock:
                    if served[0] >= requests:
                        return
                    served[0] += 1
                rows = int(r.randint(1, 5))
                srv.infer("m", {"x": r.rand(rows, 64).astype("f4")})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        with _gc_quiesced():
            t0 = _time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = _time.perf_counter() - t0
        assert wall >= min_window_s, (
            f"quant A/B timed window {wall*1e3:.0f} ms is shorter than "
            f"the {min_window_s:.1f} s floor — GC-pause-sized windows "
            f"are noise; raise `requests` (currently {requests})")
        lat = srv.latency_ms()
        return {"rps": round(requests / wall, 2), "window_s": round(wall, 3),
                "p50_ms": lat["p50"], "p99_ms": lat["p99"]}

    # -- fp32 arm ----------------------------------------------------------
    srv = serving.Server(registry, buckets=buckets, max_queue=max_queue)
    srv.load_model("m", fp32_dir)  # warms every bucket
    fp32_info = registry.models()["m"]
    # the parity ledger's reference outputs: a fixed feed through the
    # fp32 version, re-run after the quant publish for the direct diff
    ref_feed = {"x": np.random.RandomState(7).rand(4, 64).astype("f4")}
    ref_out = np.asarray(registry.acquire("m").run(ref_feed)[0], np.float64)
    fp32_arm = window(srv)
    srv.stop()

    # metrics stream starts here: publish compile lane + parity event +
    # quant steady state, one file gateable per the docstring recipe
    logger = monitor.attach_logger(MonitorLogger(metrics_path))
    steps0 = monitor.counter("executor.steps").value

    # -- quant publish: the verification ladder INCLUDING the parity gate --
    atol = float(fluid.flags.flag("FLAGS_serving_quant_atol") or 0.0)
    serving.publish(registry, "m", quant_dir, warm_buckets=buckets)
    quant_info = registry.models()["m"]
    gate_ev = [r for r in monitor.step_records()
               if r.get("kind") == "serving_event"
               and r.get("action") == "quant_parity"]
    quant_out = np.asarray(registry.acquire("m").run(ref_feed)[0], np.float64)
    max_diff = float(np.max(np.abs(quant_out - ref_out)))
    # every step record before this point is publish-lane (warm compiles,
    # golden smoke, the parity gate's reference run): the recompile-flat
    # gate must start past them
    publish_lane_steps = monitor.counter("executor.steps").value - steps0
    rec0 = monitor.counter("executor.recompile").value

    # -- quant arm (same registry: warm executable cache, same buckets) ----
    srv = serving.Server(registry, buckets=buckets, max_queue=max_queue)
    quant_arm = window(srv)
    quant_recompiles = monitor.counter("executor.recompile").value - rec0
    assert quant_recompiles == 0, (
        f"quant arm compiled inline ({quant_recompiles} recompiles) — the "
        f"publish ladder's pre-swap warm lane must leave every bucket "
        f"shape compiled before the swap")
    logger.write_snapshot()
    monitor.detach_logger(logger)
    srv.stop()
    monitor.disable()

    device = _jax.default_backend()
    on_tpu = device == "tpu"
    speedup = (quant_arm["rps"] / fp32_arm["rps"]
               if fp32_arm["rps"] else 0.0)
    hbm_sav = (1.0 - quant_info["bytes"] / fp32_info["bytes"]
               if fp32_info["bytes"] else 0.0)
    roof = _serve_roofline(fp32_dir, max(buckets))
    parity = {
        "max_abs_diff": max_diff, "atol": atol,
        "within_atol": bool(max_diff <= atol),
        "gate_event_recorded": bool(gate_ev),
        "gate_max_abs_diff": gate_ev[-1]["max_abs_diff"] if gate_ev else None,
    }
    print(f"serve-quant: fp32 {fp32_arm['rps']:.0f} req/s p99 "
          f"{fp32_arm['p99_ms']:.1f} ms ({fp32_info['bytes']/1e3:.1f} KB) "
          f"-> {quant_info['precision']} {quant_arm['rps']:.0f} req/s p99 "
          f"{quant_arm['p99_ms']:.1f} ms ({quant_info['bytes']/1e3:.1f} KB, "
          f"x{speedup:.3f}); parity max|diff| {max_diff:.2e} <= atol "
          f"{atol:g}: {parity['within_atol']} [device={device}]",
          file=sys.stderr)
    return {"metric": "serving_quant_ab_rps", "value": quant_arm["rps"],
            "unit": "req/sec", "device": device,
            "throughput_claim": ("measured_on_device" if on_tpu
                                 else "parity_only_off_device"),
            "quant_speedup": round(speedup, 4),
            "quant_throughput_ge_fp32": bool(speedup >= 1.0),
            "fp32": {**fp32_arm, "hbm_bytes": fp32_info["bytes"],
                     "precision": fp32_info["precision"]},
            "quant": {**quant_arm, "hbm_bytes": quant_info["bytes"],
                      "precision": quant_info["precision"],
                      "serve_dtype": serve_dtype,
                      "weight_bits": weight_bits},
            "hbm_savings_frac": round(hbm_sav, 4),
            "parity": parity,
            "mfu_predicted_roofline": roof.get("mfu_predicted_roofline"),
            "recompiles_steady": quant_recompiles,
            "publish_lane_steps": publish_lane_steps,
            "gate_steady_after": publish_lane_steps + 2,
            "requests": requests, "clients": clients,
            "buckets": list(buckets), "max_queue": max_queue,
            "metrics_path": metrics_path}


def bench_serve_fleet(requests=1000, clients=6, replica_counts=(1, 2, 4),
                      buckets=(1, 2, 4, 8), hb_interval_s=0.2):
    """Fleet serving bench (ISSUE 18): closed-loop rps/p99 through the
    health-aware router at 1/2/4 replicas, plus a CHAOS arm that
    SIGKILLs a replica mid-window at n=2 and prices the failover.

    Each arm spawns a REAL multi-process fleet (replica Server processes
    under the supervisor, per-request TCP through the router), drives
    `requests` closed-loop requests from `clients` threads, and records
    client-observed rps/p50/p99 — the wire + routing overhead is the
    point, so latency is measured at the caller, not inside the replica.

    The chaos arm re-runs the n=2 shape, kills rank 0 a third of the way
    in, and reports survivor-carried rps, the exact shed ledger (every
    loss must be a classified `replica_down` — the router's exactly-once
    accounting is part of what's priced), and the post-run
    `serve_trace --fleet --check` / `perf_report --check-roll-convergence`
    verdicts over the fleet's own telemetry.

    On a CPU container the absolute rps is plumbing evidence only
    (`throughput_claim="parity_only_off_device"`, same contract as
    BENCH_r06's serving round); the replica-scaling ratios and the
    chaos-arm loss bound are platform-independent."""
    import os
    import signal
    import subprocess
    import tempfile
    import threading

    import jax as _jax
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.errors import ServingError
    from paddle_tpu.serving import ServingFleet

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = layers.data("x", [64], dtype="float32")
        h = layers.fc(x, 128, act="relu")
        out = layers.fc(h, 10, act="softmax")
    startup.random_seed = 7
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    work = tempfile.mkdtemp(prefix="pt-serve-fleet-bench-")
    model_dir = os.path.join(work, "model")
    fluid.io.save_inference_model(model_dir, ["x"], [out], exe, main_p,
                                  scope)
    device = _jax.default_backend()

    def run_arm(n, chaos=False):
        root = os.path.join(work, f"fleet{n}{'.chaos' if chaos else ''}")
        fleet = ServingFleet({"m": model_dir}, n_replicas=n, root=root,
                             buckets=buckets, hb_interval_s=hb_interval_s)
        lat_ms, errs, lock = [], [], threading.Lock()
        issued = [0]
        try:
            fleet.wait_healthy(timeout=180)

            def client(seed):
                r = np.random.RandomState(seed)
                while True:
                    with lock:
                        if issued[0] >= requests:
                            return
                        issued[0] += 1
                    rows = int(r.randint(1, 5))
                    feeds = {"x": r.rand(rows, 64).astype("f4")}
                    t0 = _time.perf_counter()
                    try:
                        fleet.infer("m", feeds)
                        ms = (_time.perf_counter() - t0) * 1e3
                        with lock:
                            lat_ms.append(ms)
                    except ServingError as e:
                        with lock:
                            errs.append(e.reason)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(clients)]
            with _gc_quiesced():
                t0 = _time.perf_counter()
                for t in threads:
                    t.start()
                if chaos:
                    # let ~1/3 of the window elapse, then kill rank 0;
                    # the supervisor restarts it inside the window
                    while True:
                        with lock:
                            if issued[0] >= requests // 3:
                                break
                        _time.sleep(0.005)
                    with fleet._lock:
                        fleet._replicas[0]["proc"].send_signal(
                            signal.SIGKILL)
                for t in threads:
                    t.join()
                wall = _time.perf_counter() - t0
            ledger = fleet.stats()
            if chaos:
                # the arm also prices recovery: the supervisor must
                # restore full capacity before the fleet shuts down (the
                # --min-healthy-replicas gate below reads the final
                # snapshot)
                fleet.wait_healthy(timeout=180)
        finally:
            fleet.stop()
        arr = np.asarray(lat_ms) if lat_ms else np.asarray([0.0])
        rec = {"replicas": n, "rps": round(len(lat_ms) / wall, 1),
               "wall_s": round(wall, 3),
               "p50_ms": round(float(np.percentile(arr, 50)), 2),
               "p99_ms": round(float(np.percentile(arr, 99)), 2),
               "completed": len(lat_ms), "lost": len(errs),
               "loss_reasons": sorted(set(errs)),
               "ledger_exact": bool(
                   ledger["requests"] == ledger["completed"]
                   + ledger["errors"])}
        if chaos:
            # every loss classified, bounded by one replica's in-flight
            rec["losses_all_classified"] = all(
                r == "replica_down" for r in errs)
            rec["loss_bound"] = fleet.router.inflight_cap + 1
            tools = os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools")
            rec["fleet_check_rc"] = subprocess.call(
                [sys.executable, os.path.join(tools, "serve_trace.py"),
                 "--fleet", "--check", root],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            rec["perf_gate_rc"] = subprocess.call(
                [sys.executable, os.path.join(tools, "perf_report.py"),
                 "--check", os.path.join(root, "telemetry",
                                         "router.jsonl"),
                 "--min-healthy-replicas", str(n),
                 "--check-roll-convergence"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        return rec

    arms = {n: run_arm(n) for n in replica_counts}
    chaos = run_arm(2, chaos=True)
    base2 = arms.get(2, arms[max(arms)])
    overhead = (round(1.0 - chaos["rps"] / base2["rps"], 4)
                if base2["rps"] else None)
    for n, a in sorted(arms.items()):
        print(f"serve-fleet n={n}: {a['rps']} req/s p50 {a['p50_ms']} ms "
              f"p99 {a['p99_ms']} ms (lost {a['lost']})", file=sys.stderr)
    print(f"serve-fleet chaos n=2 (SIGKILL rank0 mid-window): "
          f"{chaos['rps']} req/s, lost {chaos['lost']} "
          f"(all classified: {chaos['losses_all_classified']}, "
          f"bound {chaos['loss_bound']}), rps overhead "
          f"{overhead if overhead is not None else 'n/a'}; "
          f"fleet_check rc={chaos['fleet_check_rc']} "
          f"perf_gate rc={chaos['perf_gate_rc']}", file=sys.stderr)
    return {"metric": "serve_fleet_rps", "value": base2["rps"],
            "unit": "req/sec", "device": device,
            "throughput_claim": ("measured" if device == "tpu"
                                 else "parity_only_off_device"),
            "replica_curve": {str(n): a for n, a in sorted(arms.items())},
            "chaos_arm": chaos, "chaos_rps_overhead_frac": overhead,
            "scaling_note": (
                "single-host replicas contend for the same cores, so the "
                "off-device replica curve prices wire+routing overhead "
                "and failover correctness, NOT horizontal scaling"
                if device != "tpu" else "per-chip replicas"),
            "requests_per_arm": requests, "clients": clients,
            "buckets": list(buckets)}


def bench_chaos(steps=48, batch_size=256, max_inflight=3,
                fault_spec="bad_batch@5;nan@13;device@21:UNAVAILABLE;"
                           "device@29:RESOURCE_EXHAUSTED"):
    """Throughput under a fixed fault schedule: the same seeded MLP run
    twice through `resilient_train_loop` — once clean, once with the
    fault injector delivering one of each recoverable class — reporting
    both rates, the recovery ledger, and the end-state parity check that
    the chaos run's params match what the surviving batches should
    produce.  The resilience overhead (snapshots + per-step resolution
    under skip_step) is the metric: it is the price of not dying."""
    import paddle_tpu as fluid
    from paddle_tpu import monitor
    from tools.perf_report import retry_fraction

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data("x", [64], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, 256, act="relu")
        h = fluid.layers.fc(h, 256, act="relu")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    startup.random_seed = main_p.random_seed = 7
    rng = np.random.RandomState(0)
    feeds = []
    for _ in range(steps):
        xv = rng.rand(batch_size, 64).astype("f4")
        feeds.append({"x": xv, "y": xv.sum(1, keepdims=True)})

    def run(injector, nan_mode):
        exe = fluid.Executor(fluid.TPUPlace(0))
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        t0 = _time.perf_counter()
        stats = fluid.resilient_train_loop(
            exe, main_p, lambda: list(feeds), [loss], scope=scope,
            injector=injector, nan_mode=nan_mode,
            policy=fluid.RetryPolicy(backoff_base_s=0.0),
            max_inflight=max_inflight, log_period=8)
        return stats, _time.perf_counter() - t0

    run(None, "raise")  # warmup/compile outside both timing windows
    monitor.enable()
    clean_stats, clean_wall = run(None, "raise")
    monitor.reset()  # recovery_frac must count the chaos run's steps only
    chaos_stats, chaos_wall = run(fluid.FaultInjector(fault_spec),
                                  "skip_step")
    frac = retry_fraction(monitor.step_records())
    monitor.disable()
    clean_sps = clean_stats.steps / clean_wall
    chaos_sps = chaos_stats.steps / chaos_wall
    # expected committed steps: each bad batch and each skip_step'd NaN
    # drops exactly one batch from the schedule; retries drop none
    from paddle_tpu.faults import parse_fault_spec

    dropped = sum(1 for f in parse_fault_spec(fault_spec)
                  if f.kind in ("bad_batch", "nan"))
    survived = bool(chaos_stats.steps == steps - dropped)
    print(f"chaos: clean {clean_sps:.1f} steps/s, faulted {chaos_sps:.1f} "
          f"steps/s (skipped {chaos_stats.skipped_batches} batches, "
          f"{chaos_stats.skipped_steps} steps, {chaos_stats.retries} "
          f"retries)", file=sys.stderr)
    return {"metric": "chaos_train_steps_per_sec", "value": round(chaos_sps, 2),
            "unit": "steps/sec", "clean_steps_per_sec": round(clean_sps, 2),
            "chaos_overhead": round(1.0 - chaos_sps / clean_sps, 4)
            if clean_sps else 0.0,
            "fault_spec": fault_spec, "steps": chaos_stats.steps,
            "survived": survived,
            "skipped_batches": chaos_stats.skipped_batches,
            "skipped_steps": chaos_stats.skipped_steps,
            "retries": chaos_stats.retries,
            "degraded_inflight": chaos_stats.degraded_inflight,
            "final_max_inflight": chaos_stats.final_max_inflight,
            "recovery_frac": round(frac, 4),
            "batch_size": batch_size, "max_inflight": max_inflight}


def bench_chaos_data(fault_spec="corrupt_chunk@2", steps=32, batch_size=64,
                     budget=4, chunk_records=64):
    """Data-corruption A/B (ISSUE 5): the same seeded MLP trained from a
    RecordIO-backed checkpointable reader pipeline twice — once over
    pristine files, once after the fault injector mutates chunks ON DISK
    (`corrupt_chunk@N` / `truncated_file@N` via `on_files`) with a corrupt
    budget armed.  Reports both rates, the corrupt-chunk ledger
    (`data.corrupt_chunks` / `data.chunks_scanned`), and how many batches
    survived — the cost of tolerating rotting storage as a number."""
    import os
    import shutil
    import tempfile

    import paddle_tpu as fluid
    from paddle_tpu import monitor, recordio
    from paddle_tpu import reader as rd
    from paddle_tpu.faults import FaultInjector

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data("x", [16], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, 64, act="relu")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    startup.random_seed = main_p.random_seed = 7

    root = tempfile.mkdtemp(prefix="pt-chaos-data-")
    rng = np.random.RandomState(0)
    path = os.path.join(root, "train.rio")
    recordio.write_arrays(
        path,
        [(rng.rand(16).astype("f4"),) for _ in range(steps * batch_size)],
        max_chunk_records=chunk_records)

    def make_factory(p):
        def to_feed(samples):
            xv = np.stack([s[0] for s in samples])
            return {"x": xv, "y": xv.sum(1, keepdims=True)}

        def factory():
            return rd.map_readers(
                to_feed, rd.batch(recordio.reader_creator(p), batch_size,
                                  drop_last=True))

        return factory

    def run(p):
        recordio.reset_corrupt_spent()
        exe = fluid.Executor(fluid.TPUPlace(0))
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        t0 = _time.perf_counter()
        stats = fluid.resilient_train_loop(
            exe, main_p, make_factory(p), [loss], scope=scope,
            policy=fluid.RetryPolicy(backoff_base_s=0.0),
            max_inflight=3, log_period=8)
        return stats, _time.perf_counter() - t0

    run(path)  # warmup/compile outside both timing windows
    monitor.enable()
    clean_stats, clean_wall = run(path)
    corrupt_path = os.path.join(root, "train_corrupt.rio")
    shutil.copyfile(path, corrupt_path)
    injector = FaultInjector(fault_spec)
    injector.on_files([corrupt_path])
    monitor.reset()
    fluid.set_flags({"FLAGS_data_corrupt_budget": budget})
    try:
        chaos_stats, chaos_wall = run(corrupt_path)
    finally:
        fluid.set_flags({"FLAGS_data_corrupt_budget": 0})
    counters = monitor.get_monitor().counter_values()
    monitor.disable()
    clean_sps = clean_stats.steps / clean_wall
    chaos_sps = chaos_stats.steps / chaos_wall if chaos_wall else 0.0
    corrupt = int(counters.get("data.corrupt_chunks", 0))
    scanned = int(counters.get("data.chunks_scanned", 0))
    print(f"chaos-data: clean {clean_sps:.1f} steps/s, corrupted "
          f"{chaos_sps:.1f} steps/s ({corrupt}/{scanned} chunks dropped, "
          f"{clean_stats.steps - chaos_stats.steps} batch(es) lost)",
          file=sys.stderr)
    return {"metric": "chaos_data_train_steps_per_sec",
            "value": round(chaos_sps, 2), "unit": "steps/sec",
            "clean_steps_per_sec": round(clean_sps, 2),
            "corrupt_overhead": round(1.0 - chaos_sps / clean_sps, 4)
            if clean_sps else 0.0,
            "fault_spec": fault_spec, "budget": budget,
            "corrupt_chunks": corrupt, "chunks_scanned": scanned,
            "data_corrupt_frac": round(corrupt / scanned, 5) if scanned else 0.0,
            "clean_steps": clean_stats.steps, "chaos_steps": chaos_stats.steps,
            "batches_lost": clean_stats.steps - chaos_stats.steps,
            "survived": bool(chaos_stats.steps > 0),
            "batch_size": batch_size, "chunk_records": chunk_records}


def bench_chaos_storage(fault_spec="enospc@12", steps=36, batch_size=256,
                        save_every=6, max_inflight=3):
    """Storage-fault A/B (ISSUE 15): the same seeded MLP trained under
    `resilient_train_loop` with periodic checkpoints twice — once on
    healthy storage, once with the fault injector failing the io.py choke
    point (`enospc@S` / `ro_fs@S` / `eio@N` / `slow_io@N:MS`).  Reports
    both rates, the DEGRADED WINDOW (steps training ran past its last
    committed checkpoint while the store failed), the recovery overhead
    (retries + skipped save rounds as wall-clock), and the parity bit:
    storage faults drop no batches, so the chaos run's end-state params
    must be BIT-IDENTICAL to the clean run's — surviving the store is
    free of training-semantics cost by construction, and this proves it."""
    import tempfile

    import paddle_tpu as fluid
    from paddle_tpu import monitor
    from paddle_tpu.checkpoint_manager import CheckpointManager
    # parity via the integrity module's full-state content digest — ONE
    # digest definition shared with the sentinel, not another hand-rolled
    # scope hash that could silently drift from it
    from paddle_tpu.integrity import state_digest as digest

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data("x", [64], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, 256, act="relu")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    startup.random_seed = main_p.random_seed = 7
    rng = np.random.RandomState(0)
    feeds = []
    for _ in range(steps):
        xv = rng.rand(batch_size, 64).astype("f4")
        feeds.append({"x": xv, "y": xv.sum(1, keepdims=True)})

    def run(spec):
        exe = fluid.Executor(fluid.TPUPlace(0))
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        cm = CheckpointManager(tempfile.mkdtemp(prefix="pt-chaos-storage-"),
                               program=main_p, scope=scope,
                               save_every_steps=save_every)
        t0 = _time.perf_counter()
        stats = fluid.resilient_train_loop(
            exe, main_p, lambda: list(feeds), [loss], scope=scope,
            injector=fluid.FaultInjector(spec) if spec else None,
            checkpoint_manager=cm,
            policy=fluid.RetryPolicy(backoff_base_s=0.0),
            max_inflight=max_inflight, log_period=8)
        return stats, _time.perf_counter() - t0, cm, digest(scope)

    run(None)  # warmup/compile outside both timing windows
    monitor.enable()
    clean_stats, clean_wall, _, clean_sha = run(None)
    monitor.reset()  # the storage ledger must count the chaos run only
    chaos_stats, chaos_wall, cm, chaos_sha = run(fault_spec)
    counters = monitor.get_monitor().counter_values()
    degraded = [r for r in monitor.step_records()
                if r.get("kind") == "resilience_event"
                and r.get("action") in ("storage_degraded",
                                        "ckpt_round_skipped")]
    recovered = [r for r in monitor.step_records()
                 if r.get("kind") == "resilience_event"
                 and r.get("action") == "storage_recovered"]
    monitor.disable()
    clean_sps = clean_stats.steps / clean_wall
    chaos_sps = chaos_stats.steps / chaos_wall if chaos_wall else 0.0
    # degraded window: first failed save round -> the recovering commit
    # (steps of training that ran with no durable checkpoint behind them)
    window = 0
    if degraded:
        end = recovered[0]["at_step"] if recovered \
            else chaos_stats.steps
        window = int(end - degraded[0]["at_step"]
                     + degraded[0].get("lag_steps", 0))
    parity = bool(chaos_sha == clean_sha)
    print(f"chaos-storage: clean {clean_sps:.1f} steps/s, faulted "
          f"{chaos_sps:.1f} steps/s ({len(degraded)} degraded round(s), "
          f"window {window} steps, recovered={bool(recovered)}, "
          f"parity={parity})", file=sys.stderr)
    return {"metric": "chaos_storage_train_steps_per_sec",
            "value": round(chaos_sps, 2), "unit": "steps/sec",
            "clean_steps_per_sec": round(clean_sps, 2),
            "storage_overhead": round(1.0 - chaos_sps / clean_sps, 4)
            if clean_sps else 0.0,
            "fault_spec": fault_spec, "steps": chaos_stats.steps,
            "survived": bool(chaos_stats.steps == steps),
            "degraded_rounds": len(degraded),
            "degraded_window_steps": window,
            "recovered": bool(recovered),
            "save_retries": int(counters.get(
                "resilience.ckpt_save_retries", 0)),
            "storage_errors": int(counters.get(
                "resilience.ckpt_storage_errors", 0)),
            "committed_saves": int(counters.get("checkpoint.saves", 0)),
            "parity": parity,
            "batch_size": batch_size, "save_every": save_every,
            "max_inflight": max_inflight}


def bench_overlap(steps=16, n_procs=2, bucket_mb=4.0, batch_size=256,
                  width=1024, depth=4):
    """2-process backward-overlapped gradient all-reduce A/B (ISSUE 7):
    the same seeded MLP trained through real multi-process gangs
    (paddle_tpu.launch.run_gang) under three grad-sync arms —

      serial    one flat all-reduce after the whole backward (the
                fetch-barrier baseline)
      bucketed  size-capped buckets issued as grads become ready,
                reverse-topological order (CompiledProgram.
                with_grad_overlap; FLAGS_dp_bucket_mb-shaped)
      gspmd     the pre-ISSUE-7 GSPMD-derived collectives, for reference

    Reports each arm's gang rate plus the acceptance checks: the bucketed
    arm must beat the serial baseline and the two must end bit-identical
    (bucketing never changes what each grad element is summed with).  The
    micro-version of this A/B (no process overhead, production bucketing
    code) is tools/collective_bench.py --overlap."""
    import os

    from paddle_tpu.launch import run_gang

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", "dist_worker_overlap.py")

    def one(mode):
        res = run_gang(
            [sys.executable, worker], n_procs,
            extra_env={"GRAD_SYNC_MODE": mode, "RUN_STEPS": str(steps),
                       "BUCKET_MB": str(bucket_mb),
                       "BATCH_SIZE": str(batch_size),
                       "MODEL_WIDTH": str(width),
                       "MODEL_DEPTH": str(depth)},
            max_restarts=0, timeout=540)
        assert res.ok, f"{mode} overlap gang failed: {res.workers}"
        recs = _gang_results(res)
        assert len(recs) == n_procs, f"{mode}: got {len(recs)} RESULT lines"
        shas = {r["params_sha"] for r in recs}
        assert len(shas) == 1, f"{mode}: ranks diverged: {shas}"
        # gang rate: the slowest worker's window is the gang's window
        wall = max(r["wall_s"] for r in recs)
        return {"steps_per_sec": round(steps / wall, 3),
                "wall_s": round(wall, 4), "params_sha": shas.pop(),
                "last_loss": recs[0]["last_loss"],
                "skew": _gang_skew(res)}

    arms = {m: one(m) for m in ("serial", "bucketed", "gspmd")}
    parity = arms["serial"]["params_sha"] == arms["bucketed"]["params_sha"]
    speedup = (arms["bucketed"]["steps_per_sec"]
               / arms["serial"]["steps_per_sec"])
    print(f"overlap: serial {arms['serial']['steps_per_sec']:.2f} steps/s, "
          f"bucketed {arms['bucketed']['steps_per_sec']:.2f} steps/s "
          f"(x{speedup:.3f}), gspmd {arms['gspmd']['steps_per_sec']:.2f} "
          f"steps/s, bit-parity={parity}", file=sys.stderr)
    return {"metric": "dp_grad_overlap_ab_steps_per_sec",
            "value": arms["bucketed"]["steps_per_sec"], "unit": "steps/sec",
            "serial_steps_per_sec": arms["serial"]["steps_per_sec"],
            "bucketed_steps_per_sec": arms["bucketed"]["steps_per_sec"],
            "gspmd_steps_per_sec": arms["gspmd"]["steps_per_sec"],
            "speedup_vs_serial": round(speedup, 4),
            "overlap_confirmed": bool(speedup > 1.0),
            "bit_parity_serial_vs_bucketed": bool(parity),
            "last_loss": arms["bucketed"]["last_loss"],
            # the bucketed arm's cross-rank skew record (trace_merge over
            # the gang's telemetry) — perf_report --check-bench gates it
            **arms["bucketed"].get("skew", {}),
            "n_procs": n_procs, "steps": steps, "bucket_mb": bucket_mb,
            "batch_size": batch_size}


def bench_chaos_dist(fault_spec, steps=12, n_procs=2, save_every=3,
                     max_restarts=2, elastic=False):
    """Multi-worker chaos benchmark: the same 2-worker sync-SGD gang run
    uninterrupted and under a distributed fault schedule
    (kill_worker@S:RANK / stall_worker@S:RANK:SECS), both through
    `paddle_tpu.launch.run_gang` + the resilient gang worker.  Reports
    both gang rates, the restart ledger, and the end-state parity check —
    gang-restart overhead (detection + rollback + relaunch + replay) as a
    number, the multi-worker analogue of the single-process chaos bench
    above.

    `elastic=True` (ISSUE 9) switches every arm to the elastic worker
    (checkpointable sharded streams, elastic CheckpointManager) and adds
    a THIRD arm: the same kill under `run_gang(elastic=True)` — the gang
    shrinks to N-1, keeps training, and grows back when capacity
    returns.  The record reports resize overhead and the post-resize
    (final grown incarnation) throughput next to the fixed-size restart
    baseline.  Elastic parity is allclose-grade, not bit-grade: a
    different world size reassociates the dp mean (docs/robustness.md)."""
    import os
    import tempfile

    from paddle_tpu.launch import run_gang

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests",
                          "dist_worker_elastic.py" if elastic
                          else "dist_worker_resilient.py")
    env = {"RUN_STEPS": str(steps), "SAVE_EVERY": str(save_every),
           "FLAGS_dist_heartbeat_interval_s": "0.25",
           "FLAGS_dist_heartbeat_miss_factor": "12",
           "FLAGS_dist_watchdog_timeout_s": "60"}
    if elastic:
        # the grow decision needs the shrunk gang to live long enough to
        # observe its commit; a tiny per-step sleep keeps the window open
        env["PT_STEP_SLEEP"] = "0.05"

    def one(spec, restarts, run_elastic=False):
        root = tempfile.mkdtemp(prefix="pt-chaos-dist-")
        e = dict(env)
        if spec:
            e["FLAGS_fault_spec"] = spec
        t0 = _time.perf_counter()
        res = run_gang([sys.executable, worker], n_procs,
                       checkpoint_root=root, extra_env=e,
                       max_restarts=restarts, timeout=540,
                       elastic=run_elastic, min_procs=1)
        wall = _time.perf_counter() - t0
        shas = [r["params_sha"] for r in _gang_results(res)]
        return res, wall, shas

    clean_res, clean_wall, clean_shas = one(None, 0)
    assert clean_res.ok, "clean gang run failed; chaos numbers meaningless"
    chaos_res, chaos_wall, chaos_shas = one(fault_spec, max_restarts)
    parity = bool(chaos_res.ok and clean_shas and chaos_shas
                  and len(set(clean_shas + chaos_shas)) == 1)
    clean_sps = steps / clean_wall
    chaos_sps = steps / chaos_wall if chaos_res.ok else 0.0
    print(f"chaos-dist: clean {clean_sps:.2f} steps/s, faulted "
          f"{chaos_sps:.2f} steps/s ({chaos_res.restarts} gang restart(s), "
          f"parity={parity})", file=sys.stderr)
    rec = {"metric": "chaos_dist_train_steps_per_sec",
           "value": round(chaos_sps, 3), "unit": "steps/sec",
           "clean_steps_per_sec": round(clean_sps, 3),
           "gang_restart_overhead": round(1.0 - chaos_sps / clean_sps, 4)
           if clean_sps and chaos_sps else None,
           "fault_spec": fault_spec, "n_procs": n_procs, "steps": steps,
           "survived": bool(chaos_res.ok),
           "gang_restarts": chaos_res.restarts,
           "incarnations": chaos_res.incarnations,
           "worker_deaths": [d for i in chaos_res.incidents
                             for d in i.get("dead", [])],
           # cross-rank skew over the CLEAN gang's telemetry (the chaos
           # arm's skew measures the injected fault, not the gang)
           **_gang_skew(clean_res),
           "telemetry_dir": chaos_res.telemetry_dir,
           "bit_parity_vs_clean": parity}
    if not elastic:
        return rec
    el_res, el_wall, el_shas = one(fault_spec, max_restarts,
                                   run_elastic=True)
    el_sps = steps / el_wall if el_res.ok else 0.0
    # post-resize throughput: the final (grown-back) incarnation's own
    # rate, from its RESULT line — what the gang sustains once capacity
    # is back, with the resize machinery out of the hot path
    post_sps = None
    final = _gang_results(el_res)
    if el_res.ok and final:
        r0 = final[0]
        if r0.get("steps_run") and r0.get("wall_s"):
            post_sps = round(r0["steps_run"] / r0["wall_s"], 3)
    print(f"chaos-dist --elastic: {el_sps:.2f} steps/s end-to-end "
          f"({el_res.resizes} resize(s), sizes {el_res.size_history}), "
          f"post-resize {post_sps} steps/s vs fixed-restart "
          f"{chaos_sps:.2f}", file=sys.stderr)
    rec["elastic"] = {
        "steps_per_sec": round(el_sps, 3),
        "post_resize_steps_per_sec": post_sps,
        "resize_overhead": round(1.0 - el_sps / clean_sps, 4)
        if clean_sps and el_sps else None,
        "fixed_restart_steps_per_sec": round(chaos_sps, 3),
        "survived": bool(el_res.ok),
        "resizes": el_res.resizes,
        "size_history": el_res.size_history,
        "resize_events": el_res.resize_events,
        "incarnations": el_res.incarnations,
        "ranks_agree": bool(el_res.ok and len(set(el_shas)) == 1),
    }
    return rec


def bench_chaos_integrity(fault_spec="rot_shard@1", steps=24, save_every=4,
                          batch_size=64, n_procs=2, max_restarts=2):
    """Silent-corruption chaos A/B (ISSUE 14).

    rot_shard specs run single-process: train with periodic commits while
    the injector flips a byte of the Nth COMMITTED checkpoint post-COMMIT,
    then a fresh process resumes — the at-rest digests must reject the
    rotted snapshot (`integrity.ckpt_rejected`), the walk-back lands one
    earlier, and the resumed run must end bit-identical to a resume from
    a pristine tree.  The record reports the walk-back ledger and the
    resume-time overhead of paying one extra restore.

    flip_bit specs route to a 2-process gang on the integrity worker
    (FLAGS_integrity_check_period armed): the live digests must diverge,
    the vote must name the flipped rank, the gang restarts from the
    newest quarantine-clean checkpoint, and the final params must be
    bit-identical to an uninterrupted gang — detection + restart + replay
    overhead as a number."""
    import os
    import tempfile

    import paddle_tpu as fluid
    from paddle_tpu import monitor
    from paddle_tpu.faults import FaultInjector, parse_fault_spec

    kinds = {f.kind for f in parse_fault_spec(fault_spec)}
    if "flip_bit" in kinds:
        from paddle_tpu.launch import run_gang

        worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "tests", "dist_worker_integrity.py")
        env = {"RUN_STEPS": str(steps), "SAVE_EVERY": str(save_every),
               "INTEGRITY_PERIOD": "2", "PT_STEP_SLEEP": "0.02",
               "FLAGS_dist_heartbeat_interval_s": "0.1",
               "FLAGS_dist_heartbeat_miss_factor": "30",
               "FLAGS_dist_watchdog_timeout_s": "60"}

        def one(spec, restarts):
            root = tempfile.mkdtemp(prefix="pt-chaos-integrity-")
            e = dict(env)
            if spec:
                e["FLAGS_fault_spec"] = spec
            t0 = _time.perf_counter()
            res = run_gang([sys.executable, worker], n_procs,
                           checkpoint_root=root, extra_env=e,
                           max_restarts=restarts, timeout=540)
            return res, _time.perf_counter() - t0

        clean_res, clean_wall = one(None, 0)
        assert clean_res.ok, "clean gang run failed; chaos numbers " \
                             "meaningless"
        chaos_res, chaos_wall = one(fault_spec, max_restarts)
        clean_shas = [r["params_sha"] for r in _gang_results(clean_res)]
        chaos_shas = [r["params_sha"] for r in _gang_results(chaos_res)]
        # the verdict is printed by the DETECTING incarnation, whose
        # workers exit classified without a RESULT line — harvest it
        # from the full per-incarnation stderr history
        import re as _re

        named = set()
        for inc in chaos_res.history:
            for _code, _out, err in inc:
                for m in _re.finditer(
                        r"INTEGRITY_FAILURE corrupt_ranks=\[([\d, ]*)\]",
                        err or ""):
                    named.update(int(x) for x in m.group(1).split(",")
                                 if x.strip())
        named = sorted(named)
        parity = bool(chaos_res.ok and clean_shas and chaos_shas
                      and len(set(clean_shas + chaos_shas)) == 1)
        print(f"chaos-integrity: flip_bit detected "
              f"(corrupt rank(s) {named}), {chaos_res.restarts} gang "
              f"restart(s), parity={parity}", file=sys.stderr)
        return {"metric": "chaos_integrity_flip_bit",
                "value": round(chaos_wall - clean_wall, 3),
                "unit": "sec_recovery_overhead",
                "fault_spec": fault_spec, "corrupt_ranks_named": named,
                "gang_restarts": chaos_res.restarts,
                "bit_parity": parity, "steps": steps}

    # rot_shard: single-process commit-rot-resume A/B
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data("x", [32], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, 64, act="relu")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    startup.random_seed = main_p.random_seed = 7
    rng = np.random.RandomState(0)
    feeds = []
    for _ in range(steps):
        xv = rng.rand(batch_size, 32).astype("f4")
        feeds.append({"x": xv, "y": xv.sum(1, keepdims=True)})

    def train(root, injector, resume, n):
        exe = fluid.Executor(fluid.TPUPlace(0))
        scope = fluid.Scope()
        if not resume:
            exe.run(startup, scope=scope)
        cm = fluid.CheckpointManager(root, program=main_p, scope=scope,
                                     save_every_steps=save_every)
        t0 = _time.perf_counter()
        stats = fluid.resilient_train_loop(
            exe, main_p, lambda: list(feeds), [loss], scope=scope,
            checkpoint_manager=cm, resume=resume, injector=injector,
            max_inflight=1, max_steps=n)
        from paddle_tpu import integrity as _integ

        return stats, _time.perf_counter() - t0, _integ.state_digest(scope)

    half = steps // 2
    monitor.enable()
    root_a = tempfile.mkdtemp(prefix="pt-rot-clean-")
    root_b = tempfile.mkdtemp(prefix="pt-rot-chaos-")
    train(root_a, None, False, half)
    train(root_b, FaultInjector(fault_spec), False, half)
    rej0 = monitor.counter("integrity.ckpt_rejected").value
    _, clean_wall, clean_sha = train(root_a, None, True, steps)
    _, chaos_wall, chaos_sha = train(root_b, None, True, steps)
    rejected = monitor.counter("integrity.ckpt_rejected").value - rej0
    monitor.disable()
    parity = bool(clean_sha == chaos_sha)
    print(f"chaos-integrity: rot_shard rejected {rejected} checkpoint(s) "
          f"on resume, walk-back overhead "
          f"{chaos_wall - clean_wall:+.3f}s, parity={parity}",
          file=sys.stderr)
    return {"metric": "chaos_integrity_rot_shard",
            "value": round(chaos_wall - clean_wall, 3),
            "unit": "sec_walkback_overhead",
            "fault_spec": fault_spec, "ckpt_rejected": int(rejected),
            "bit_parity": parity, "steps": steps,
            "survived": bool(rejected >= 1 and parity)}


def bench_online(steps=48, publish_every=8, batch_size=512, feat=8,
                 dim=16, base_vocab=4096, table_scales=(1, 4),
                 chaos_spec="kill_pserver@18", staleness_bound_steps=None):
    """Online-learning round (ISSUE 19): a CTR model whose embedding
    table lives HOST-TIERED (hot head in process, cold tail on a
    supervised parameter-server child) trains under
    `resilient_train_loop` while the publish hook streams verified
    sparse snapshots into a serving `ModelRegistry` every
    `publish_every` steps.

    Arms: one clean run per table scale (1x / 4x an HBM-equivalent base
    table — on this container "HBM-equivalent" prices BYTES MOVED
    through the host tier, not a real device budget), plus a chaos arm
    that SIGKILLs the pserver child mid-run (`kill_pserver@S` via the
    fault injector).  Each arm reports examples/sec and the
    publish-to-serving staleness ledger (max trained-step minus
    last-published-step, from the `serving.publish_staleness_steps`
    gauge the loop maintains); the chaos arm additionally requires
    bit-identical table recovery (server digest before kill == after
    restart-and-replay at the same op count is the unit-tested
    invariant; here the END-TO-END check is that every published
    snapshot passed the ladder, cadence held, and the staleness bound
    declared in this record was never exceeded), and the arm's own
    metrics stream must pass `perf_report --check
    --max-publish-staleness-steps` (gate rc embedded in the record)."""
    import os
    import subprocess
    import tempfile

    import paddle_tpu as fluid
    from paddle_tpu import io, layers, monitor
    from paddle_tpu.core.selected_rows import SelectedRows
    from paddle_tpu.faults import FaultInjector
    from paddle_tpu.monitor import MonitorLogger
    from paddle_tpu.parallel.embedding import TieredEmbedding
    from paddle_tpu.param_server import KVClient, PServerSupervisor
    from paddle_tpu.serving import ModelRegistry, publish

    bound = (2 * publish_every if staleness_bound_steps is None
             else int(staleness_bound_steps))
    # a pserver kill costs at most the client-retry window in degraded
    # steps; one publish period is the declared recovery budget
    lag_bound = publish_every

    # training program: the embedding block arrives as a FEED (pulled
    # from the tiered table per batch); calc_gradient taps the grad to
    # push back — the host-table pattern of tests/test_param_server.py
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        emb = layers.data("emb", [feat * dim], dtype="float32")
        label = layers.data("label", [1], dtype="float32")
        h = layers.fc(emb, 64, act="relu",
                      param_attr=fluid.ParamAttr(name="ol_h"),
                      bias_attr=fluid.ParamAttr(name="ol_hb"))
        pred = layers.fc(h, 1, param_attr=fluid.ParamAttr(name="ol_p"),
                         bias_attr=False)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, label))
        (emb_grad,) = fluid.calc_gradient(loss, [emb])
        fluid.optimizer.SGD(0.1).minimize(
            loss, parameter_list=["ol_h", "ol_hb", "ol_p"])
    startup.random_seed = main_p.random_seed = 7

    def serving_program(vocab):
        sp, st = fluid.Program(), fluid.Program()
        with fluid.program_guard(sp, st):
            ids = layers.data("ids", [feat], dtype="int64")
            e = layers.embedding(ids, size=[vocab, dim], is_sparse=True,
                                 param_attr=fluid.ParamAttr(name="ol_tbl"))
            h = layers.fc(layers.reshape(e, [-1, feat * dim]), 64,
                          act="relu",
                          param_attr=fluid.ParamAttr(name="ol_h"),
                          bias_attr=fluid.ParamAttr(name="ol_hb"))
            pr = layers.fc(h, 1, param_attr=fluid.ParamAttr(name="ol_p"),
                           bias_attr=False)
        st.random_seed = 7
        return sp, st, pr

    def run_arm(scale, chaos=False):
        vocab = base_vocab * scale
        root = tempfile.mkdtemp(prefix=f"pt-online-x{scale}-")
        metrics = os.path.join(root, "metrics.jsonl")
        monitor.enable()
        logger = monitor.attach_logger(MonitorLogger(metrics))
        sup = PServerSupervisor(os.path.join(root, "ps"),
                                optimizer="sgd", lr=0.1,
                                snapshot_every_ops=64).start()
        sup.wait_ready()
        client = KVClient(sup.endpoint)
        tiered = TieredEmbedding(client, "ol_tbl", vocab, dim,
                                 hot_rows=vocab // 4, lr=0.1, seed=3)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)

        # serving side: boot the registry on the step-0 table
        sprog, sstart, spred = serving_program(vocab)
        sscope = fluid.Scope()
        exe.run(sstart, scope=sscope)
        reg = ModelRegistry(place=fluid.CPUPlace())
        snames = [v.name for v in io._persistables(sprog)]

        def snapshot_dir(step):
            d = os.path.join(root, f"snap-{step:06d}")
            pub = fluid.Scope()
            pub.set_var("ol_tbl", tiered.export_selected_rows())
            for n in snames:
                if n != "ol_tbl":
                    v = scope.find_var(n)
                    assert v is not None, f"dense var {n!r} not trained"
                    pub.set_var(n, np.asarray(v))
            io.save_sharded(d, snames, pub, program=sprog,
                            process_index=0)
            return d

        d0 = os.path.join(root, "model-0")
        sscope.set_var("ol_tbl",
                       np.asarray(tiered.export_selected_rows()))
        for n in snames:
            if n != "ol_tbl":
                v = scope.find_var(n)
                assert v is not None, f"dense var {n!r} not in train scope"
                sscope.set_var(n, np.asarray(v))
        io.save_inference_model(d0, ["ids"], [spred], exe, sprog, sscope)
        reg.load("ctr", d0)

        rng = np.random.RandomState(scale)
        ids_stream = [rng.randint(0, vocab, size=(batch_size, feat))
                      for _ in range(steps)]
        w_true = rng.rand(feat * dim, 1).astype("f4")

        def loader():
            for ids in ids_stream:
                e = tiered.lookup(ids).reshape(batch_size, feat * dim)
                yield {"emb": e, "label": e @ w_true}

        step_ids = {"i": 0}

        def on_logged(step, vals):
            ids = ids_stream[step_ids["i"] % steps]
            step_ids["i"] += 1
            g = np.asarray(vals[1]).reshape(-1, dim)
            tiered.apply_grad(ids.reshape(-1), g)

        published = []

        def publish_hook(step):
            d = snapshot_dir(step)
            if injector is not None:
                injector.on_commit(d)
            published.append(step)
            publish(reg, "ctr", d)

        injector = None
        if chaos:
            injector = FaultInjector(chaos_spec).set_pserver(sup)
        t0 = _time.perf_counter()
        stats = fluid.resilient_train_loop(
            exe, main_p, loader, [loss, emb_grad], scope=scope,
            injector=injector, max_inflight=1, log_period=1,
            on_logged=on_logged, publish_hook=publish_hook,
            publish_period_steps=publish_every,
            policy=fluid.RetryPolicy(backoff_base_s=0.0))
        wall = _time.perf_counter() - t0
        from tools.perf_report import publish_staleness_steps as _stale

        logger.write_snapshot()  # final counter/gauge state for the gates
        monitor.detach_logger(logger)
        counters = monitor.get_monitor().counter_values()
        with open(metrics) as f:
            lines = [json.loads(ln) for ln in f if ln.strip()]
        staleness = _stale(lines)
        monitor.disable()
        monitor.reset()
        tools = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tools")
        # --steady-after past the run: every publish stages a FRESH
        # scope, so the ladder's verification compile moves the global
        # recompile counter each period by design — the steady-state
        # recompile gate is about the TRAINING loop's cache and is
        # skipped here, while the staleness/host-lag gates (the round's
        # contract) run against the declared bounds
        gate_rc = subprocess.call(
            [sys.executable, os.path.join(tools, "perf_report.py"),
             "--check", metrics, "--steady-after", str(steps + 2),
             "--max-publish-staleness-steps", str(bound),
             "--max-host-lag-steps", str(lag_bound)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        table_bytes = vocab * dim * 4
        client.close()
        sup.stop()
        exs = round(stats.steps * batch_size / wall, 1) if wall else 0.0
        rec = {"scale": scale, "vocab": vocab,
               "table_mb": round(table_bytes / 1e6, 2),
               "examples_per_sec": exs, "steps": stats.steps,
               "publishes": stats.publishes,
               "publish_failures": stats.publish_failures,
               "max_staleness_steps": int(staleness or 0),
               "staleness_bound_steps": bound,
               "staleness_bound_ok": bool((staleness or 0) <= bound),
               "host_lag_steps": tiered.host_lag_steps,
               "host_lag_bound_steps": lag_bound,
               "perf_gate_rc": gate_rc}
        if chaos:
            rec.update({
                "fault_spec": chaos_spec,
                "pserver_restarts": sup.restarts,
                "push_retries": int(counters.get("ps.retries", 0)),
                "push_dedup": int(counters.get("ps.push_dedup", 0)),
                "degraded_steps": int(
                    counters.get("sparse.degraded_steps", 0)),
                "survived": bool(stats.steps == steps
                                 and not sup.failed)})
        return rec

    arms = {s: run_arm(s) for s in table_scales}
    chaos = run_arm(min(table_scales), chaos=True)
    for s, a in sorted(arms.items()):
        print(f"online x{s} ({a['table_mb']} MB table): "
              f"{a['examples_per_sec']} ex/s, {a['publishes']} publishes, "
              f"max staleness {a['max_staleness_steps']} steps "
              f"(bound {a['staleness_bound_steps']}, gate "
              f"rc={a['perf_gate_rc']})", file=sys.stderr)
    print(f"online chaos ({chaos['fault_spec']}): "
          f"{chaos['examples_per_sec']} ex/s, survived="
          f"{chaos['survived']} with {chaos['pserver_restarts']} pserver "
          f"restart(s), {chaos['push_retries']} client retries, "
          f"{chaos['degraded_steps']} degraded step(s), max staleness "
          f"{chaos['max_staleness_steps']} steps (bound "
          f"{chaos['staleness_bound_steps']}, gate "
          f"rc={chaos['perf_gate_rc']})", file=sys.stderr)
    import jax as _jax

    base = arms[min(table_scales)]
    device = _jax.default_backend()
    return {"metric": "online_learning_examples_per_sec",
            "value": base["examples_per_sec"], "unit": "examples/sec",
            "device": device,
            "throughput_claim": ("measured" if device == "tpu"
                                 else "parity_only_off_device"),
            "publish_every_steps": publish_every,
            "staleness_bound_steps": bound,
            "table_curve": {str(s): a for s, a in sorted(arms.items())},
            "chaos": chaos,
            "batch_size": batch_size, "steps": steps}


def bench_chaos_campaign(seed=7, per_scenario=3, max_faults=3):
    """Chaos-campaign round (ISSUE 20): seeded multi-fault schedules
    drawn over the train / online-learning / serving scenarios
    (paddle_tpu/chaos.py), every run judged by the cross-subsystem
    invariant registry, failures shrunk to minimal repro specs.  The
    record carries the campaign ledger (schedules run, invariant checks,
    violations — 0 is the pass bar), schedules/sec as the round's
    number, and the `perf_report --check --max-chaos-violations 0`
    verdict on the campaign's own metrics stream, so the gate gates the
    gate."""
    import os
    import subprocess
    import tempfile

    from paddle_tpu import chaos

    out = tempfile.mkdtemp(prefix="pt-bench-chaos-campaign-")
    metrics = os.path.join(out, "chaos_metrics.jsonl")
    t0 = _time.perf_counter()
    res = chaos.run_campaign(scenarios=("train", "online", "serving"),
                             seed=seed, per_scenario=per_scenario,
                             out_dir=out, metrics_path=metrics,
                             max_faults=max_faults)
    wall = _time.perf_counter() - t0
    tools = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tools")
    gate_rc = subprocess.call(
        [sys.executable, os.path.join(tools, "perf_report.py"),
         "--check", metrics, "--max-chaos-violations", "0"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    print(f"chaos-campaign: {res.schedules_run} schedule(s), "
          f"{res.invariants_checked} invariant check(s), "
          f"{len(res.violations)} violation(s) in {wall:.1f}s "
          f"(gate rc={gate_rc})", file=sys.stderr)
    for v in res.violations:
        print(f"  VIOLATION {v['invariant']} [{v['class']}] on "
              f"{v['scenario']} {v['spec']!r} -> "
              f"{v.get('shrunk_spec', '(unshrunk)')}", file=sys.stderr)
    return {"metric": "chaos_campaign_schedules_per_sec",
            "value": round(res.schedules_run / wall, 3),
            "unit": "schedules/sec", "seed": seed,
            "schedules_run": res.schedules_run,
            "invariants_checked": res.invariants_checked,
            "violations": len(res.violations),
            "repro_specs": [v.get("shrunk_spec", v["spec"])
                            for v in res.violations],
            "perf_gate_rc": gate_rc, "wall_s": round(wall, 1),
            "survived": bool(not res.violations and gate_rc == 0)}


_DIST_FAULT_KINDS = ("kill_worker", "stall_worker")
_DATA_FAULT_KINDS = ("corrupt_chunk", "truncated_file")
_INTEGRITY_FAULT_KINDS = ("flip_bit", "rot_shard")
_STORAGE_FAULT_KINDS = ("enospc", "eio@", "slow_io", "ro_fs")
_PSERVER_FAULT_KINDS = ("kill_pserver", "stall_pserver", "rot_row")


def main():
    # The MFU campaign's kernels are opt-in (FLAGS_use_pallas); the bench
    # round measures them by default — platform-gated, so this is a no-op
    # off-TPU, and `--no-pallas` A/Bs the composite baseline.
    if "--no-pallas" not in sys.argv:
        import paddle_tpu as fluid

        fluid.set_flags({"FLAGS_use_pallas": True})
    per_model = "--per-model" in sys.argv
    fault_spec = None
    for i, a in enumerate(sys.argv):
        if a == "--fault-spec" and i + 1 < len(sys.argv):
            fault_spec = sys.argv[i + 1]
        elif a.startswith("--fault-spec="):
            fault_spec = a.split("=", 1)[1]
    if "--online" in sys.argv:
        if fault_spec:
            print(json.dumps(bench_online(chaos_spec=fault_spec)))
        else:
            print(json.dumps(bench_online()))
        return
    if "--pipeline" in sys.argv:
        print(json.dumps(bench_pipeline()))
        return
    if "--overlap" in sys.argv:
        print(json.dumps(bench_overlap()))
        return
    if "--serve-fleet" in sys.argv:
        print(json.dumps(bench_serve_fleet()))
        return
    if "--serve" in sys.argv:
        if "--quant" in sys.argv:
            print(json.dumps(bench_serve_quant()))
        else:
            print(json.dumps(bench_serve()))
        return
    if "--chaos-campaign" in sys.argv:
        seed = 7
        for i, a in enumerate(sys.argv):
            if a == "--seed" and i + 1 < len(sys.argv):
                seed = int(sys.argv[i + 1])
            elif a.startswith("--seed="):
                seed = int(a.split("=", 1)[1])
        print(json.dumps(bench_chaos_campaign(seed=seed)))
        return
    if "--chaos" in sys.argv:
        # distributed entries route to the multi-worker gang bench, data
        # entries to the RecordIO corruption A/B; plain specs keep the
        # single-process resilient-loop bench
        if fault_spec and any(k in fault_spec for k in _PSERVER_FAULT_KINDS):
            # host-tier chaos rides the online-learning bench (the only
            # arm with a pserver child + sparse publish cadence to hurt)
            print(json.dumps(bench_online(chaos_spec=fault_spec)))
        elif fault_spec and any(k in fault_spec for k in _DIST_FAULT_KINDS):
            print(json.dumps(bench_chaos_dist(
                fault_spec, elastic="--elastic" in sys.argv)))
        elif fault_spec and any(k in fault_spec
                                for k in _INTEGRITY_FAULT_KINDS):
            print(json.dumps(bench_chaos_integrity(fault_spec)))
        elif fault_spec and any(k in fault_spec for k in _DATA_FAULT_KINDS):
            print(json.dumps(bench_chaos_data(fault_spec)))
        elif fault_spec and any(k in fault_spec
                                for k in _STORAGE_FAULT_KINDS):
            print(json.dumps(bench_chaos_storage(fault_spec)))
        elif fault_spec:
            print(json.dumps(bench_chaos(fault_spec=fault_spec)))
        else:
            print(json.dumps(bench_chaos()))
        return
    only = None
    for a in sys.argv[1:]:
        if not a.startswith("-"):
            only = a
    results = {}
    benches = [("mnist", bench_mnist), ("nmt", bench_nmt), ("bert", bench_bert),
               ("deepfm", bench_deepfm), ("resnet50", bench_resnet50)]
    for name, fn in benches:
        if only and name != only:
            continue
        for attempt in (0, 1):
            try:
                results[name] = fn()
                break
            except Exception as e:  # a broken side model must not kill the flagship
                transient = "remote_compile" in str(e) or "read body" in str(e)
                if transient and attempt == 0:
                    # the tunnel's remote-compile endpoint drops connections
                    # occasionally; one retry covers it (observed r5)
                    print(f"{name}: transient tunnel error, retrying", file=sys.stderr)
                    continue
                results[name] = {"metric": name, "error": f"{type(e).__name__}: {e}"}
                print(f"{name} FAILED: {e}", file=sys.stderr)
                break

    if per_model or only:
        for name, r in results.items():
            print(json.dumps(r))
        return

    flag = results.get("resnet50", {})
    imgs = flag.get("value", 0.0)
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": imgs,
        "unit": "imgs/sec",
        "vs_baseline": round(imgs / ROUND1_IMGS_PER_SEC, 4) if imgs else 0.0,
        "extra": {
            "mfu_bf16_analytic": flag.get("mfu_bf16_analytic"),
            "spread_pct": flag.get("spread_pct"),
            "windows_ms": flag.get("windows_ms"),
            "batch_size": flag.get("batch_size"),
            "steps_per_dispatch": flag.get("steps_per_dispatch"),
            # params_moved must ride the wrapper or check_bench's
            # dead-optimizer-state gate can never fire for the flagship
            "params_moved": flag.get("params_moved"),
            "vs_baseline_is": "this_round_imgs_per_sec / round1_imgs_per_sec",
            "models": {k: v for k, v in results.items() if k != "resnet50"},
        },
    }))


if __name__ == "__main__":
    main()
