"""Benchmark driver covering every BASELINE.md target (reference harness:
benchmark/fluid/fluid_benchmark.py — one driver, many models).

Default invocation prints ONE JSON line: the flagship ResNet-50 metric with
every other model's result embedded under extra.models.  `--per-model`
prints one JSON line per model instead (mnist parity gate, resnet50,
transformer NMT ragged path, BERT-base, DeepFM CTR).

vs_baseline: the reference published no numbers (BASELINE.md), so the
absolute series is tracked across rounds; vs_baseline = this round's
imgs/s over round-1's 2295.

MFU numbers are computed from analytic FLOPs (the tunnel backend's
cost_analysis() is broken — returns 4.2 GFLOP for a full ResNet train
step); labeled `*_analytic`.
"""
from __future__ import annotations

import json
import statistics
import sys
import time

import numpy as np

ROUND1_IMGS_PER_SEC = 2295.0  # BENCH_r01.json
V5E_BF16_PEAK = 197e12


def _sync(x):
    return np.asarray(x)


def _timed_steps(dispatch, n_warm=2, iters=3, windows=1):
    """best-of-N timing windows: the shared-chip pool shows ~±20% run-to-run
    throughput variance, so the minimum window is the honest compute time.
    All window times are returned so results can report spread —
    round-over-round deltas are only meaningful against it."""
    for _ in range(n_warm):
        out = dispatch()
    _sync(out[0])
    ws = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = dispatch()
        _sync(out[0])
        ws.append((time.perf_counter() - t0) / iters)
    return min(ws), out, [round(w * 1e3, 3) for w in ws]


def _spread(ws):
    """(max-min)/median over windows, %; same stat as tools/opbench.py."""
    if len(ws) < 2:
        return 0.0
    return round((max(ws) - min(ws)) / statistics.median(ws) * 100, 1)


def bench_resnet50(batch_size=256, K=4, iters=4):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    main, startup, feeds, fetches = resnet.build(
        dtype="bfloat16", class_dim=1000, learning_rate=0.1, with_optimizer=True,
        stem="space_to_depth")
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    dev = fluid.TPUPlace(0).jax_device()
    feed = {
        "img": jax.device_put(jnp.asarray(rng.rand(K, batch_size, 3, 224, 224), jnp.float32), dev),
        "label": jax.device_put(jnp.asarray(
            rng.randint(0, 1000, (K, batch_size, 1)), jnp.int32), dev),
    }
    loss_name = fetches["loss"].name

    def dispatch():
        return exe.run(main, feed=feed, fetch_list=[loss_name], scope=scope,
                       steps=K, return_numpy=False)

    dt, out, ws = _timed_steps(dispatch, iters=iters, windows=3)
    dt /= K
    ws = [round(w / K, 3) for w in ws]
    lossN = float(np.asarray(out[0]).reshape(-1)[-1])
    assert np.isfinite(lossN), f"non-finite resnet loss {lossN}"
    imgs = batch_size / dt
    mfu = imgs * 3 * 4.089e9 / V5E_BF16_PEAK
    print(f"resnet50: {dt*1e3:.1f} ms  {imgs:.0f} imgs/s  mfu {mfu:.3f}", file=sys.stderr)
    return {"metric": "resnet50_train_imgs_per_sec_per_chip", "value": round(imgs, 2),
            "unit": "imgs/sec", "mfu_bf16_analytic": round(mfu, 4),
            "batch_size": batch_size, "steps_per_dispatch": K,
            "windows_ms": ws, "spread_pct": _spread(ws)}


def bench_mnist(batch_size=128, steps=40):
    """Loss-parity gate (BASELINE: 'loss parity vs CPU ref'): the same
    seeded program must converge on the chip and match a rerun bit-for-bit
    modulo accelerator numerics (rtol 1e-3 on the loss curve)."""
    import paddle_tpu as fluid
    from paddle_tpu.models import mnist

    rng = np.random.RandomState(0)
    # strongly learnable synthetic task (random labels would floor the CE
    # at ln10): each class k brightens the image by 0.06*k, so class is
    # linearly decodable from mean brightness and the net leaves the prior
    # floor within a few dozen steps
    labels = rng.randint(0, 10, (steps, batch_size)).astype("int64")
    imgs = (rng.rand(steps, batch_size, 1, 28, 28) * 0.4
            + labels[..., None, None, None] * 0.06).astype("float32")
    labels = labels[..., None]

    def run(place):
        main, startup, feeds, fetches = mnist.build(learning_rate=1e-3)
        startup.random_seed = 7
        scope = fluid.Scope()
        exe = fluid.Executor(place)
        exe.run(startup, scope=scope)
        losses = []
        t0 = time.perf_counter()
        for i in range(steps):
            (lv,) = exe.run(main, feed={"img": imgs[i], "label": labels[i]},
                            fetch_list=[fetches["loss"]], scope=scope)
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        return losses, time.perf_counter() - t0

    tpu_losses, dt = run(fluid.TPUPlace(0))
    cpu_losses, _ = run(fluid.CPUPlace())
    parity = bool(np.allclose(tpu_losses, cpu_losses, rtol=5e-2, atol=1e-3))
    converged = tpu_losses[-1] < tpu_losses[0] * 0.7
    imgs_per_sec = batch_size * steps / dt
    print(f"mnist: parity={parity} converged={converged} "
          f"loss {tpu_losses[0]:.3f}->{tpu_losses[-1]:.3f}", file=sys.stderr)
    return {"metric": "mnist_loss_parity", "value": imgs_per_sec, "unit": "imgs/sec",
            "parity_vs_cpu": parity, "converged": bool(converged),
            "first_loss": round(tpu_losses[0], 4), "last_loss": round(tpu_losses[-1], 4)}


def bench_nmt(iters=6):
    """Transformer-base NMT on the ragged/LoD path: seqs/sec with bucketed
    variable-length batches (BASELINE: 'no CUDA ops in executed program' —
    trivially true: every op lowers to XLA)."""
    import paddle_tpu as fluid
    from paddle_tpu.models import nmt

    main, startup, feeds, fetches = nmt.build_transformer_nmt(
        src_vocab=8000, tgt_vocab=8000, d_model=512, n_layers=6, n_heads=8,
        d_ff=2048, dropout=0.1, learning_rate=2.0)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    b = 32
    batches = []
    for _ in range(2):
        ls = rng.randint(20, 64, size=b).tolist()
        lt = rng.randint(20, 64, size=b).tolist()
        batches.append(nmt.make_fake_nmt_batch(ls, lt, 8000, 8000))
    for batch in batches:  # compile both buckets
        exe.run(main, feed=batch, fetch_list=[fetches["loss"]], scope=scope)
    t0 = time.perf_counter()
    n = 0
    for i in range(iters):
        (lv,) = exe.run(main, feed=batches[i % 2], fetch_list=[fetches["loss"]],
                        scope=scope)
        n += b
    lv = float(np.asarray(lv).reshape(-1)[0])
    dt = time.perf_counter() - t0
    assert np.isfinite(lv)
    seqs = n / dt
    print(f"nmt: {seqs:.0f} seqs/s  loss {lv:.3f}", file=sys.stderr)
    return {"metric": "transformer_nmt_train_seqs_per_sec_per_chip",
            "value": round(seqs, 2), "unit": "seqs/sec", "batch_size": b,
            "config": "base-6L-512d ragged"}


def bench_bert(batch_size=256, seq_len=128, iters=4):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer

    main, startup, feeds, fetches = transformer.build_bert(
        vocab_size=30522, seq_len=seq_len, d_model=768, n_layers=12, n_heads=12,
        d_ff=3072, dropout_prob=0.1, with_optimizer=True, dtype="bfloat16")
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    batch = transformer.make_fake_batch(batch_size, seq_len, 30522)
    dev = fluid.TPUPlace(0).jax_device()
    batch = {k: jax.device_put(jnp.asarray(v), dev) for k, v in batch.items()}
    loss_name = fetches["loss"].name

    def dispatch():
        return exe.run(main, feed=batch, fetch_list=[loss_name], scope=scope,
                       return_numpy=False)

    dt, out, ws = _timed_steps(dispatch, iters=iters, windows=2)
    lossN = float(np.asarray(out[0]).reshape(-1)[-1])
    assert np.isfinite(lossN)
    seqs = batch_size / dt
    # analytic train FLOPs/seq for BERT-base @128: ~6 * 110e6 params * 128 tokens
    flops_per_seq = 6 * 110e6 * seq_len
    mfu = seqs * flops_per_seq / V5E_BF16_PEAK
    print(f"bert: {dt*1e3:.1f} ms  {seqs:.0f} seqs/s  mfu {mfu:.3f}", file=sys.stderr)
    return {"metric": "bert_base_train_seqs_per_sec_per_chip", "value": round(seqs, 2),
            "unit": "seqs/sec", "mfu_bf16_analytic": round(mfu, 4),
            "batch_size": batch_size, "seq_len": seq_len,
            "windows_ms": ws, "spread_pct": _spread(ws)}


def bench_deepfm(batch_size=4096, iters=8):
    import paddle_tpu as fluid
    from paddle_tpu.core import lowering
    from paddle_tpu.models import deepfm

    main, startup, feeds, fetches = deepfm.build(
        num_fields=26, vocab_size=200000, embed_dim=16, mlp_dims=(400, 400, 400),
        learning_rate=0.05)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 200000, (batch_size, 26))
    label = (rng.rand(batch_size, 1) < 0.3).astype("float32")
    feed = {"feat_ids": ids, "label": label}

    def dispatch():
        return exe.run(main, feed=feed, fetch_list=[fetches["loss"]], scope=scope,
                       return_numpy=False)

    dt, out, ws = _timed_steps(dispatch, iters=iters)
    lossN = float(np.asarray(out[0]).reshape(-1)[0])
    assert np.isfinite(lossN)
    sparse = sorted(lowering.LAST_TRACE_REPORT.get("sparse_grad_params", []))
    ex = batch_size / dt
    print(f"deepfm: {ex:.0f} ex/s  sparse={sparse}", file=sys.stderr)
    return {"metric": "deepfm_ctr_train_examples_per_sec_per_chip",
            "value": round(ex, 2), "unit": "examples/sec",
            "batch_size": batch_size, "vocab": 200000,
            "sparse_grad_params": sparse}


def main():
    per_model = "--per-model" in sys.argv
    only = None
    for a in sys.argv[1:]:
        if not a.startswith("-"):
            only = a
    results = {}
    benches = [("mnist", bench_mnist), ("nmt", bench_nmt), ("bert", bench_bert),
               ("deepfm", bench_deepfm), ("resnet50", bench_resnet50)]
    for name, fn in benches:
        if only and name != only:
            continue
        try:
            results[name] = fn()
        except Exception as e:  # a broken side model must not kill the flagship
            results[name] = {"metric": name, "error": f"{type(e).__name__}: {e}"}
            print(f"{name} FAILED: {e}", file=sys.stderr)

    if per_model or only:
        for name, r in results.items():
            print(json.dumps(r))
        return

    flag = results.get("resnet50", {})
    imgs = flag.get("value", 0.0)
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": imgs,
        "unit": "imgs/sec",
        "vs_baseline": round(imgs / ROUND1_IMGS_PER_SEC, 4) if imgs else 0.0,
        "extra": {
            "mfu_bf16_analytic": flag.get("mfu_bf16_analytic"),
            "spread_pct": flag.get("spread_pct"),
            "windows_ms": flag.get("windows_ms"),
            "batch_size": flag.get("batch_size"),
            "steps_per_dispatch": flag.get("steps_per_dispatch"),
            "vs_baseline_is": "this_round_imgs_per_sec / round1_imgs_per_sec",
            "models": {k: v for k, v in results.items() if k != "resnet50"},
        },
    }))


if __name__ == "__main__":
    main()
