"""Benchmark driver: prints ONE JSON line with the flagship metric.

Flagship: ResNet-50 ImageNet training throughput on one TPU chip, bf16
compute (reference harness: benchmark/fluid/fluid_benchmark.py, which
printed `Throughput` per pass; BASELINE.md target is >=50% MFU).
vs_baseline is vs the reference's published numbers — it published none
(BASELINE.md), so 1.0 marks parity-by-default and the absolute value is
the series to track across rounds.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def bench_resnet50(batch_size=64, warmup=3, iters=20):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    main, startup, feeds, fetches = resnet.build(
        dtype="bfloat16", class_dim=1000, learning_rate=0.1, with_optimizer=True
    )
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    img = rng.rand(batch_size, 3, 224, 224).astype("float32")
    label = rng.randint(0, 1000, size=(batch_size, 1)).astype(np.int32)
    # device-resident synthetic batch (reference harness: --use_fake_data in
    # benchmark/fluid/fluid_benchmark.py) so the tunnel's H2D bandwidth
    # doesn't pollute the compute measurement
    import jax.numpy as jnp

    dev = fluid.TPUPlace(0).jax_device()
    feed = {
        "img": jax.device_put(jnp.asarray(img), dev),
        "label": jax.device_put(jnp.asarray(label), dev),
    }
    loss_name = fetches["loss"].name

    for _ in range(warmup):
        out = exe.run(main, feed=feed, fetch_list=[loss_name], scope=scope, return_numpy=False)
    loss0 = float(np.asarray(out[0])[0])  # hard sync (block_until_ready is
    # advisory on the axon tunnel backend)

    t0 = time.perf_counter()
    for _ in range(iters):
        out = exe.run(main, feed=feed, fetch_list=[loss_name], scope=scope, return_numpy=False)
    lossN = float(np.asarray(out[0])[0])  # hard sync: value read drains the chain
    dt = (time.perf_counter() - t0) / iters

    imgs_per_sec = batch_size / dt
    # ResNet-50 fwd ~4.09 GFLOP/img at 224^2; train ~3x fwd.
    train_flops_per_img = 3 * 4.089e9
    achieved = imgs_per_sec * train_flops_per_img
    peak = 197e12  # v5e bf16 peak FLOP/s
    mfu = achieved / peak
    print(f"step {dt*1e3:.1f} ms  loss {lossN:.3f}  mfu {mfu:.3f}", file=sys.stderr)
    return imgs_per_sec, mfu


def main():
    batch = 128
    imgs_per_sec, mfu = bench_resnet50(batch_size=batch)
    print(
        json.dumps(
            {
                "metric": "resnet50_train_imgs_per_sec_per_chip",
                "value": round(imgs_per_sec, 2),
                "unit": "imgs/sec",
                "vs_baseline": 1.0,
                "extra": {"mfu_bf16": round(mfu, 4), "batch_size": batch},
            }
        )
    )


if __name__ == "__main__":
    main()
