"""Benchmark driver: prints ONE JSON line with the flagship metric.

Flagship: ResNet-50 ImageNet training throughput on one TPU chip, bf16
compute (reference harness: benchmark/fluid/fluid_benchmark.py, which
printed `Throughput` per pass; BASELINE.md target is >=50% MFU — see
docs/perf_r02.md for the measured breakdown of the gap).

vs_baseline: the reference published no numbers (BASELINE.md), so the
absolute imgs/s series is what's tracked across rounds; vs_baseline is
this round's value over the round-1 recorded value (2295 imgs/s) so
regressions are visible, NOT parity vs the reference.

MFU is computed from analytic FLOPs (3x 4.089 GFLOP/img) because the
tunnel backend's compiled-program cost_analysis() is broken (returns
4.2 GFLOP for a full train step).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

ROUND1_IMGS_PER_SEC = 2295.0  # BENCH_r01.json


def bench_resnet50(batch_size=128, steps_per_dispatch=8, warmup=1, iters=4):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    main, startup, feeds, fetches = resnet.build(
        dtype="bfloat16", class_dim=1000, learning_rate=0.1, with_optimizer=True
    )
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)

    K = steps_per_dispatch
    rng = np.random.RandomState(0)
    img = rng.rand(K, batch_size, 3, 224, 224).astype("float32")
    label = rng.randint(0, 1000, size=(K, batch_size, 1)).astype(np.int32)
    # device-resident synthetic batch (reference harness: --use_fake_data in
    # benchmark/fluid/fluid_benchmark.py) so the tunnel's H2D bandwidth
    # doesn't pollute the compute measurement
    dev = fluid.TPUPlace(0).jax_device()
    feed = {
        "img": jax.device_put(jnp.asarray(img), dev),
        "label": jax.device_put(jnp.asarray(label), dev),
    }
    loss_name = fetches["loss"].name

    def dispatch():
        # steps=K scans K optimizer steps inside one compiled call,
        # amortizing host/tunnel dispatch overhead (docs/perf_r02.md)
        return exe.run(main, feed=feed, fetch_list=[loss_name], scope=scope,
                       steps=K, return_numpy=False)

    out = dispatch()
    np.asarray(out[0])  # hard sync (block_until_ready is advisory on the tunnel)
    for _ in range(warmup):
        out = dispatch()
    np.asarray(out[0])

    t0 = time.perf_counter()
    for _ in range(iters):
        out = dispatch()
    losses = np.asarray(out[0])  # hard sync: value read drains the chain
    dt = (time.perf_counter() - t0) / (iters * K)
    lossN = float(losses[-1])
    if not np.isfinite(lossN):
        raise RuntimeError(f"non-finite loss from bench step: {lossN}")

    imgs_per_sec = batch_size / dt
    # ResNet-50 fwd ~4.09 GFLOP/img at 224^2; train ~3x fwd (analytic; see
    # module docstring for why XLA cost analysis isn't used here).
    train_flops_per_img = 3 * 4.089e9
    peak = 197e12  # v5e bf16 peak FLOP/s
    mfu = imgs_per_sec * train_flops_per_img / peak
    print(f"step {dt*1e3:.1f} ms  loss {lossN:.3f}  mfu {mfu:.3f}", file=sys.stderr)
    return imgs_per_sec, mfu


def main():
    batch = 128
    steps_per_dispatch = 8
    imgs_per_sec, mfu = bench_resnet50(
        batch_size=batch, steps_per_dispatch=steps_per_dispatch
    )
    print(
        json.dumps(
            {
                "metric": "resnet50_train_imgs_per_sec_per_chip",
                "value": round(imgs_per_sec, 2),
                "unit": "imgs/sec",
                "vs_baseline": round(imgs_per_sec / ROUND1_IMGS_PER_SEC, 4),
                "extra": {
                    "mfu_bf16_analytic": round(mfu, 4),
                    "batch_size": batch,
                    "steps_per_dispatch": steps_per_dispatch,
                    "vs_baseline_is": "this_round_imgs_per_sec / round1_imgs_per_sec",
                },
            }
        )
    )


if __name__ == "__main__":
    main()
